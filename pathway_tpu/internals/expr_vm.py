"""Lower expression ASTs to native stack-VM bytecode.

The reference evaluates typed expression trees row-wise entirely in Rust
(``src/engine/expression.rs:26-491``) — no Python in the select/filter
hot loop.  This module is the TPU build's equivalent front half: it walks
the (build-time-typed) :mod:`pathway_tpu.internals.expression` AST and
emits a flat postfix program for the C++ VM in
``native/pathway_native.cpp`` (``vm_eval_batch``/``vm_filter_batch``).

Lazy constructs (``if_else``/``coalesce``/``fill_error``/``get`` default)
compile to jump-based code so only the taken branch evaluates — the same
observable behaviour as the Python closures.  High-traffic
``.dt``/``.str``/``.num`` namespace methods lower to ``OP_METHOD`` with a
native implementation per method (reference evaluates these enums in Rust,
``src/engine/expression.rs:26-340``); subtrees with no native lowering
(UDF ``apply``, zoneinfo conversions) fall back to their
ordinary ``_compile`` closure, embedded as a single ``CALL_PY``
instruction; the rest of the expression still runs native.

Every op's behaviour is pinned to the Python closure semantics by the
differential tests in ``tests/test_expr_vm.py`` (native program vs pure
Python closure over a value matrix including ``None`` and ``ERROR``).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import keys
from pathway_tpu.internals import native as _native

# opcodes — must mirror enum VmOp in native/pathway_native.cpp
OP_LOAD_COL = 1
OP_LOAD_KEY = 2
OP_LOAD_CONST = 3
OP_CALL_PY = 4
OP_BIN = 5
OP_NEG = 6
OP_INV = 7
OP_IS_NONE = 8
OP_BRANCH = 9
OP_JUMP = 10
OP_JUMP_NOT_NONE = 11
OP_POP = 12
OP_REQUIRE = 13
OP_UNWRAP = 14
OP_FILL_JUMP = 15
OP_CAST = 16
OP_CONVERT = 17
OP_MAKE_TUPLE = 18
OP_GET = 19
OP_POINTER = 20
OP_METHOD = 21

# (method name, operand count) -> native method id — must mirror enum
# VmMethod in native/pathway_native.cpp.  str.split maps BOTH arities to
# one id — the native op distinguishes whitespace vs separator splitting
# by operand count.  to_utc / to_naive_in_timezone carry their zone's
# packed transition table (internals/tztable.py) as a constant operand,
# so the zoneinfo database is consulted at graph build, not per row.
_METHOD_IDS = {
    ("str.lower", 1): 0,
    ("str.upper", 1): 1,
    ("str.swapcase", 1): 2,
    ("str.title", 1): 3,
    ("str.reversed", 1): 4,
    ("str.len", 1): 5,
    ("str.strip", 1): 6,
    ("str.strip", 2): 6,
    ("str.lstrip", 1): 7,
    ("str.lstrip", 2): 7,
    ("str.rstrip", 1): 8,
    ("str.rstrip", 2): 8,
    ("str.count", 2): 9,
    ("str.find", 3): 10,
    ("str.find", 4): 10,
    ("str.rfind", 3): 11,
    ("str.rfind", 4): 11,
    ("str.startswith", 2): 12,
    ("str.endswith", 2): 13,
    ("str.replace", 4): 14,
    ("str.slice", 3): 15,
    ("str.parse_int", 1): 16,
    ("str.parse_int_opt", 1): 17,
    ("str.parse_float", 1): 18,
    ("str.parse_float_opt", 1): 19,
    ("str.parse_bool", 3): 20,
    ("str.parse_bool_opt", 3): 21,
    ("str.parse_datetime", 2): 22,
    ("dt.strptime", 2): 22,
    ("dt.nanosecond", 1): 23,
    ("dt.microsecond", 1): 24,
    ("dt.millisecond", 1): 25,
    ("dt.second", 1): 26,
    ("dt.minute", 1): 27,
    ("dt.hour", 1): 28,
    ("dt.day", 1): 29,
    ("dt.month", 1): 30,
    ("dt.year", 1): 31,
    ("dt.day_of_week", 1): 32,
    ("dt.day_of_year", 1): 33,
    ("dt.timestamp", 2): 34,
    ("dt.strftime", 2): 35,
    ("dt.round", 2): 36,
    ("dt.floor", 2): 37,
    ("dt.nanoseconds", 1): 38,
    ("dt.microseconds", 1): 39,
    ("dt.milliseconds", 1): 40,
    ("dt.seconds", 1): 41,
    ("dt.minutes", 1): 42,
    ("dt.hours", 1): 43,
    ("dt.days", 1): 44,
    ("dt.weeks", 1): 45,
    ("num.abs", 1): 46,
    ("num.fill_na", 2): 47,
    ("num.round", 2): 48,
    ("str.split", 2): 49,  # whitespace split: (s, maxsplit)
    ("str.split", 3): 49,  # separator split: (s, sep, maxsplit)
    ("dt.from_timestamp", 2): 50,  # (x, scale)
    ("dt.utc_from_timestamp", 2): 51,  # (x, scale)
    ("dt.to_utc", 2): 52,  # (d, tz_table)
    ("dt.to_naive_in_timezone", 2): 53,  # (d, tz_table)
}

# binary op ids — must mirror enum VmBin
BIN_IDS = {
    "+": 0, "-": 1, "*": 2, "/": 3, "//": 4, "%": 5, "**": 6, "@": 7,
    "==": 8, "!=": 9, "<": 10, "<=": 11, ">": 12, ">=": 13,
    "&": 14, "|": 15, "^": 16,
}

_CAST_IDS = {dt.INT: 0, dt.FLOAT: 1, dt.BOOL: 2, dt.STR: 3}

# ---------------------------------------------------------------------------
# program shape tables — the single source of truth for code rewriting
# (fusion splices in analysis/rewrite.py, abstract interpretation in
# analysis/vm_abstract.py).  Code is a flat int list; every opcode has a
# fixed operand count, and each operand slot is exactly one of: a plain
# immediate, an absolute jump target, an index into the const pool, or an
# index into the pyfunc pool.

#: operand word count per opcode
OPERAND_WIDTHS = {
    OP_LOAD_COL: 1,
    OP_LOAD_KEY: 0,
    OP_LOAD_CONST: 1,
    OP_CALL_PY: 1,
    OP_BIN: 1,
    OP_NEG: 0,
    OP_INV: 0,
    OP_IS_NONE: 0,
    OP_BRANCH: 2,
    OP_JUMP: 1,
    OP_JUMP_NOT_NONE: 1,
    OP_POP: 0,
    OP_REQUIRE: 1,
    OP_UNWRAP: 0,
    OP_FILL_JUMP: 1,
    OP_CAST: 1,
    OP_CONVERT: 2,
    OP_MAKE_TUPLE: 1,
    OP_GET: 2,
    OP_POINTER: 3,
    OP_METHOD: 3,
}

#: operand slots holding absolute jump targets (may equal len(code) = END)
_JUMP_SLOTS = {
    OP_BRANCH: (0, 1),
    OP_JUMP: (0,),
    OP_JUMP_NOT_NONE: (0,),
    OP_REQUIRE: (0,),
    OP_FILL_JUMP: (0,),
    OP_GET: (1,),
}

#: operand slots indexing the const pool
_CONST_SLOTS = {OP_LOAD_CONST: (0,), OP_POINTER: (2,)}

#: operand slots indexing the pyfunc pool
_PYFUNC_SLOTS = {OP_CALL_PY: (0,)}


def iter_program(code: list[int]):
    """Yield ``(pc, op, operands)`` walking a flat code list.  Raises
    ``ValueError`` on an unknown opcode — rewriting a program it cannot
    fully parse would corrupt it."""
    pc = 0
    n = len(code)
    while pc < n:
        op = code[pc]
        width = OPERAND_WIDTHS.get(op)
        if width is None:
            raise ValueError(f"unknown opcode {op} at pc {pc}")
        yield pc, op, code[pc + 1 : pc + 1 + width]
        pc += 1 + width


def renumber_columns(code: list[int], mapping: Any) -> list[int]:
    """Return a copy of ``code`` with every ``OP_LOAD_COL`` operand
    remapped through ``mapping`` (a dict or callable).  The register
    renumbering primitive behind filter pushdown: a predicate compiled
    against a join's output frame (left cols ``0..ln-1``, right cols
    ``ln..ln+rn-1``) is retargeted at one side's input frame by shifting
    its column registers.  Raises ``KeyError`` when a register has no
    mapping — the caller must have proven the program only touches the
    columns being remapped."""
    out = list(code)
    get = mapping.__getitem__ if hasattr(mapping, "__getitem__") else mapping
    for pc, op, ops in iter_program(code):
        if op == OP_LOAD_COL:
            out[pc + 1] = get(ops[0])
    return out


def concat_programs(
    down: tuple[list[int], list[Any], list[Any]],
    columns: dict[int, tuple[list[int], list[Any], list[Any]]],
) -> tuple[list[int], list[Any], list[Any]]:
    """Fuse two adjacent row programs into one: inline an upstream
    select's per-column programs into a downstream program at each
    ``OP_LOAD_COL`` site.

    ``down`` and each ``columns[pos]`` are raw ``(code, consts,
    pyfuncs)`` triples (see :func:`lower_raw`).  The result evaluates
    the downstream program against the *upstream's input* frame: where
    the downstream loaded column ``pos`` of the intermediate frame, it
    now computes that column's defining program in place.  Upstream
    jump targets shift by their splice offset; downstream jump targets
    are remapped through a pc map built in the same walk (inlined code
    changes all downstream offsets); const/pyfunc indices renumber into
    the merged pools.  ``OP_LOAD_KEY`` needs no fixup — selects preserve
    row keys, so both frames share the key.

    Raises ``KeyError`` if the downstream loads a column with no
    supplied program, ``ValueError`` on unparseable code."""
    dcode, dconsts, dpy = down
    out: list[int] = []
    consts: list[Any] = []
    pyfuncs: list[Any] = []
    offsets: dict[Any, tuple[int, int]] = {}

    def _pool(key: Any, c: list[Any], p: list[Any]) -> tuple[int, int]:
        if key not in offsets:
            offsets[key] = (len(consts), len(pyfuncs))
            consts.extend(c)
            pyfuncs.extend(p)
        return offsets[key]

    pc_map: dict[int, int] = {}
    jump_fixes: list[tuple[int, int]] = []  # (out slot, old down target)
    for pc, op, ops in iter_program(dcode):
        pc_map[pc] = len(out)
        if op == OP_LOAD_COL:
            ucode, uconsts, upy = columns[ops[0]]
            coff, poff = _pool(("col", ops[0]), uconsts, upy)
            base = len(out)
            piece = list(ucode)
            for upc, uop, uops in iter_program(ucode):
                for s in _JUMP_SLOTS.get(uop, ()):
                    piece[upc + 1 + s] = base + uops[s]
                for s in _CONST_SLOTS.get(uop, ()):
                    piece[upc + 1 + s] = coff + uops[s]
                for s in _PYFUNC_SLOTS.get(uop, ()):
                    piece[upc + 1 + s] = poff + uops[s]
            out.extend(piece)
            continue
        coff, poff = _pool("down", dconsts, dpy)
        start = len(out)
        out.append(op)
        out.extend(ops)
        for s in _JUMP_SLOTS.get(op, ()):
            jump_fixes.append((start + 1 + s, ops[s]))
        for s in _CONST_SLOTS.get(op, ()):
            out[start + 1 + s] = coff + ops[s]
        for s in _PYFUNC_SLOTS.get(op, ()):
            out[start + 1 + s] = poff + ops[s]
    pc_map[len(dcode)] = len(out)
    for slot, old_t in jump_fixes:
        out[slot] = pc_map[old_t]
    return out, consts, pyfuncs


def lower_raw(e: "ex.ColumnExpression", layout: Any) -> "_Asm | None":
    """Lower one expression to an open-coded :class:`_Asm` (raw
    ``code``/``consts``/``pyfuncs`` lists) for the rewriter to splice,
    without compiling a capsule.  None when lowering fails."""
    asm = _Asm(layout)
    try:
        _lower(e, asm)
    except Exception:  # lowering must never break the rewriter
        return None
    return asm


def compile_triple(
    triple: tuple[list[int], list[Any], list[Any]]
) -> Any | None:
    """Compile a raw ``(code, consts, pyfuncs)`` triple to a VM program
    capsule, or None when the native module is absent or rejects it."""
    native = _native.load()
    if native is None:
        return None
    code, consts, pyfuncs = triple
    try:
        return native.vm_compile(list(code), tuple(consts), tuple(pyfuncs))
    except Exception:
        return None


class _Asm:
    def __init__(self, layout: Any):
        self.layout = layout
        self.code: list[int] = []
        self.consts: list[Any] = []
        self.pyfuncs: list[Any] = []
        self.native_ops = 0  # CALL_PY-only programs aren't worth running

    def emit(self, *xs: int) -> None:
        self.code.extend(xs)

    def const(self, v: Any) -> int:
        self.consts.append(v)
        return len(self.consts) - 1

    def here(self) -> int:
        return len(self.code)

    def patch(self, pos: int, val: int) -> None:
        self.code[pos] = val

    def fallback(self, e: ex.ColumnExpression) -> None:
        """Embed the subtree's ordinary Python closure as one CALL_PY."""
        fn = e._compile(self.layout.resolver)
        self.pyfuncs.append(fn)
        self.emit(OP_CALL_PY, len(self.pyfuncs) - 1)


def _lower(e: ex.ColumnExpression, asm: _Asm) -> None:
    t = type(e)
    if t is ex.ConstExpression:
        asm.emit(OP_LOAD_CONST, asm.const(e._value))
        asm.native_ops += 1
        return
    if t is ex.ColumnReference:
        pos = asm.layout.resolve_pos(e)
        if pos is None:
            asm.fallback(e)
            return
        if pos == -1:
            asm.emit(OP_LOAD_KEY)
        else:
            asm.emit(OP_LOAD_COL, pos)
        asm.native_ops += 1
        return
    if t is ex.BinaryExpression:
        bid = BIN_IDS.get(e._op)
        if bid is None:
            asm.fallback(e)
            return
        _lower(e._left, asm)
        _lower(e._right, asm)
        asm.emit(OP_BIN, bid)
        asm.native_ops += 1
        return
    if t is ex.UnaryExpression:
        _lower(e._operand, asm)
        asm.emit(OP_NEG if e._op == "-" else OP_INV)
        asm.native_ops += 1
        return
    if t is ex.IsNoneExpression:
        _lower(e._expr, asm)
        asm.emit(OP_IS_NONE)
        asm.native_ops += 1
        return
    if t is ex.IfElseExpression:
        _lower(e._cond, asm)
        asm.emit(OP_BRANCH, 0, 0)
        fix = asm.here() - 2  # (else_t, end_t)
        _lower(e._then, asm)
        asm.emit(OP_JUMP, 0)
        jfix = asm.here() - 1
        asm.patch(fix, asm.here())  # else target
        _lower(e._else, asm)
        end = asm.here()
        asm.patch(fix + 1, end)
        asm.patch(jfix, end)
        asm.native_ops += 1
        return
    if t is ex.CoalesceExpression:
        if not e._args:
            asm.emit(OP_LOAD_CONST, asm.const(None))
            asm.native_ops += 1
            return
        jumps = []
        for i, a in enumerate(e._args):
            _lower(a, asm)
            if i < len(e._args) - 1:
                asm.emit(OP_JUMP_NOT_NONE, 0)
                jumps.append(asm.here() - 1)
                asm.emit(OP_POP)
        end = asm.here()
        for j in jumps:
            asm.patch(j, end)
        asm.native_ops += 1
        return
    if t is ex.RequireExpression:
        fixes = []
        for d in e._deps:
            _lower(d, asm)
            asm.emit(OP_REQUIRE, 0)
            fixes.append(asm.here() - 1)
        _lower(e._value, asm)
        end = asm.here()
        for f in fixes:
            asm.patch(f, end)
        asm.native_ops += 1
        return
    if t is ex.CastExpression:
        tid = _CAST_IDS.get(e._target.strip_optional())
        _lower(e._expr, asm)
        if tid is None:
            return  # unknown target passes the value through (closure parity)
        asm.emit(OP_CAST, tid)
        asm.native_ops += 1
        return
    if t is ex.ConvertExpression:
        native = _native.load()
        tid = _CAST_IDS.get(e._target.strip_optional())
        if tid is None or native is None or not _json_registered(native):
            asm.fallback(e)
            return
        _lower(e._expr, asm)
        asm.emit(OP_CONVERT, tid, 1 if e._unwrap else 0)
        asm.native_ops += 1
        return
    if t is ex.MakeTupleExpression:
        for a in e._args:
            _lower(a, asm)
        asm.emit(OP_MAKE_TUPLE, len(e._args))
        asm.native_ops += 1
        return
    if t is ex.GetExpression:
        native = _native.load()
        if native is None or not _json_registered(native):
            asm.fallback(e)
            return
        _lower(e._obj, asm)
        _lower(e._index, asm)
        strict = 0 if e._check else 1
        asm.emit(OP_GET, strict, 0)
        fix = asm.here() - 1
        if e._check:
            _lower(e._default, asm)
        asm.patch(fix, asm.here())
        asm.native_ops += 1
        return
    if t is ex.UnwrapExpression:
        _lower(e._expr, asm)
        asm.emit(OP_UNWRAP)
        asm.native_ops += 1
        return
    if t is ex.FillErrorExpression:
        _lower(e._expr, asm)
        asm.emit(OP_FILL_JUMP, 0)
        fix = asm.here() - 1
        asm.emit(OP_POP)
        _lower(e._replacement, asm)
        asm.patch(fix, asm.here())
        asm.native_ops += 1
        return
    if t is ex.DeclareTypeExpression:
        _lower(e._expr, asm)
        return
    if t is ex.PointerExpression:
        # closure parity: only _args are evaluated (instance is a
        # grouping hint, not hash material — expression.py:688-698)
        for a in e._args:
            _lower(a, asm)
        rs_idx = asm.const(keys.ref_scalar)
        asm.emit(
            OP_POINTER, len(e._args), 1 if e._optional else 0, rs_idx
        )
        asm.native_ops += 1
        return
    if t is ex.MethodCallExpression:
        mid = _METHOD_IDS.get((e._method_name, len(e._args)))
        if mid is None:
            asm.fallback(e)
            return
        for a in e._args:
            _lower(a, asm)
        asm.emit(
            OP_METHOD, mid, len(e._args), 1 if e._propagate_none else 0
        )
        asm.native_ops += 1
        return
    # ApplyExpression (+async variants) and any future node types run as
    # their ordinary Python closure
    asm.fallback(e)


def _json_registered(native: Any) -> bool:
    return getattr(native, "_json_registered", False)


def lower_program(e: ex.ColumnExpression, layout: Any) -> Any | None:
    """Compile one expression to a VM program capsule, or None when the
    native module is absent or nothing in the tree lowers natively."""
    native = _native.load()
    if native is None:
        return None
    asm = _Asm(layout)
    try:
        _lower(e, asm)
    except Exception:  # lowering must never break graph build
        return None
    if asm.native_ops == 0:
        return None  # pure CALL_PY: the closure path is already optimal
    try:
        return native.vm_compile(asm.code, tuple(asm.consts), tuple(asm.pyfuncs))
    except Exception:
        return None


def lower_programs(exprs: list[ex.ColumnExpression], layout: Any) -> Any | None:
    """Capsules for a select's output columns.  A column with no native
    lowering still becomes a one-CALL_PY program (the batch loop is the
    same either way), but if NO column lowers natively the select keeps
    the existing rowwise_map closure path — identical performance, less
    machinery."""
    native = _native.load()
    if native is None:
        return None
    asms = []
    total_native = 0
    for e in exprs:
        asm = _Asm(layout)
        try:
            _lower(e, asm)
        except Exception:  # lowering must never break graph build
            return None
        total_native += asm.native_ops
        asms.append(asm)
    if total_native == 0:
        return None
    try:
        return tuple(
            native.vm_compile(a.code, tuple(a.consts), tuple(a.pyfuncs))
            for a in asms
        )
    except Exception:
        return None


def project_program(positions: list[int]) -> Any | None:
    """A program per position for pure column projection (filter's
    project-back node): LOAD_COL only."""
    native = _native.load()
    if native is None:
        return None
    try:
        return tuple(
            native.vm_compile([OP_LOAD_COL, p], (), ()) for p in positions
        )
    except Exception:
        return None
