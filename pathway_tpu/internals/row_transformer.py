"""Row transformers — the legacy class-transformer API (reference
``internals/row_transformer.py`` + ``decorators.py``:
``@pw.transformer`` classes of ``pw.ClassArg`` tables with
``pw.input_attribute`` / ``@pw.output_attribute`` / ``@pw.method``).

Rows reference OTHER rows by pointer (``self.transformer.t[ptr].attr``),
so an attribute's value can depend on an unbounded pointer walk (linked
lists, skip lists).  Execution re-design for the epoch engine: one
centralized node per output table holds every input table's rows and
lazily evaluates attributes with memoization per epoch; only rows whose
outputs changed re-emit.  (The reference tracks fine-grained per-cell
dependencies inside its engine; epoch-level memoized recompute gives
the same externally observable updates for this legacy API.)
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import cluster as cl
from pathway_tpu.engine import graph as eg
from pathway_tpu.engine.stream import Update, consolidate
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys as K
from pathway_tpu.internals.parse_graph import G

__all__ = [
    "ClassArg",
    "RowTransformer",
    "input_attribute",
    "input_method",
    "method",
    "output_attribute",
    "transformer",
]


class _InputAttribute:
    _counter = 0

    def __init__(self, type: Any = float):
        self.type = type
        _InputAttribute._counter += 1
        self.order = _InputAttribute._counter
        self.name: str | None = None  # filled by ClassArg.__init_subclass__


class _OutputAttribute:
    def __init__(self, func: Callable):
        self.func = func
        self.name = func.__name__


class _Method:
    def __init__(self, func: Callable, is_output: bool = True):
        self.func = func
        self.name = func.__name__
        self.is_output = is_output


def input_attribute(type: Any = float) -> Any:
    """Declare an input column of the class-arg table."""
    return _InputAttribute(type)


def output_attribute(func: Callable) -> _OutputAttribute:
    """Decorate a zero-arg method: becomes an output column."""
    return _OutputAttribute(func)


def method(func: Callable) -> _Method:
    """Decorate a method callable from other attributes (exposed as a
    callable column in the output, like the reference's MethodColumn)."""
    return _Method(func)


input_method = input_attribute  # reference alias surface


class ClassArg:
    """Base for a transformer's per-table argument class.  At runtime an
    instance is a ROW VIEW: ``self.id``, input attributes from the row,
    output attributes computed (and memoized) on demand."""

    _input_attrs: list[_InputAttribute]
    _output_attrs: list[_OutputAttribute]
    _methods: list[_Method]

    def __init_subclass__(cls, input: Any = None, output: Any = None, **kw: Any):
        super().__init_subclass__(**kw)
        cls._input_schema = input
        cls._output_schema = output
        ins, outs, methods = [], [], []
        for name, v in list(cls.__dict__.items()):
            if isinstance(v, _InputAttribute):
                v.name = name
                ins.append(v)
            elif isinstance(v, _OutputAttribute):
                outs.append(v)
            elif isinstance(v, _Method):
                methods.append(v)
        ins.sort(key=lambda a: a.order)
        cls._input_attrs = ins
        cls._output_attrs = outs
        cls._methods = methods
        # remove the declarations from the class so instance attribute
        # access falls through to __getattr__ (the runtime resolver)
        for spec_list in (ins, outs, methods):
            for a in spec_list:
                if a.name and hasattr(cls, a.name):
                    delattr(cls, a.name)

    # -- runtime row view -------------------------------------------------
    def __init__(self, runtime: "_Runtime", table: str, key: Any):
        self._runtime = runtime
        self._table = table
        self.id = key

    @property
    def transformer(self) -> "_Runtime":
        return self._runtime

    def pointer_from(self, *args: Any) -> K.Pointer:
        return K.ref_scalar(*args)

    def __getattr__(self, name: str):
        # called only when normal lookup fails — resolve input/output attrs
        runtime = self.__dict__.get("_runtime")
        if runtime is None:
            raise AttributeError(name)
        return runtime._resolve(self._table, self.id, name)


class _RowView:
    """Proxy for ``self.transformer.<table>[pointer]``."""

    def __init__(self, runtime: "_Runtime", table: str):
        self._runtime = runtime
        self._table = table

    def __getitem__(self, key: Any) -> Any:
        return _InstanceView(self._runtime, self._table, key)


class _InstanceView:
    def __init__(self, runtime: "_Runtime", table: str, key: Any):
        self._runtime = runtime
        self._table = table
        self.id = key

    def __getattr__(self, name: str):
        return self._runtime._resolve(self._table, self.id, name)


class _Runtime:
    """Evaluation context for one epoch: all tables' rows + memo cache."""

    def __init__(self, spec: "RowTransformer", rows: dict[str, dict]):
        self._spec = spec
        self._rows = rows  # table name -> {key: value tuple}
        self._memo: dict[tuple, Any] = {}
        self._in_progress: set[tuple] = set()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._spec.class_args:
            return _RowView(self, name)
        raise AttributeError(name)

    def _resolve(self, table: str, key: Any, name: str) -> Any:
        cls = self._spec.class_args[table]
        row = self._rows[table].get(key)
        if row is None:
            raise KeyError(f"row {key!r} not present in {table!r}")
        for i, ia in enumerate(cls._input_attrs):
            if ia.name == name:
                return row[i]
        for oa in cls._output_attrs:
            if oa.name == name:
                memo_key = (table, key, name)
                if memo_key in self._memo:
                    return self._memo[memo_key]
                if memo_key in self._in_progress:
                    raise RecursionError(
                        f"cyclic attribute dependency at {table}[{key}].{name}"
                    )
                self._in_progress.add(memo_key)
                try:
                    value = oa.func(cls(self, table, key))
                finally:
                    self._in_progress.discard(memo_key)
                self._memo[memo_key] = value
                return value
        for m in cls._methods:
            if m.name == name:
                inst = cls(self, table, key)
                return lambda *a, **kw: m.func(inst, *a, **kw)
        raise AttributeError(f"{table} has no attribute {name!r}")


class _BoundMethod:
    """A method column's value: callable, LATE-BINDING (each call reads
    the node's current rows), and equal across epochs for the same
    (table, key, method) — so method columns never make change detection
    fire for rows whose attributes did not change."""

    def __init__(self, spec, rows_ref: dict, table: str, key: Any, name: str):
        self._spec = spec
        self._rows_ref = rows_ref  # the node state's live rows dict
        self._table = table
        self._key = key
        self._name = name

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        runtime = _Runtime(self._spec, self._rows_ref)
        fn = runtime._resolve(self._table, self._key, self._name)
        return fn(*args, **kwargs)

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, _BoundMethod)
            and self._table == other._table
            and self._key == other._key
            and self._name == other._name
        )

    def __hash__(self) -> int:
        return hash((self._table, self._key, self._name))

    def __repr__(self) -> str:
        return f"<method {self._table}[{self._key!r}].{self._name}>"


class _RowTransformerNode(eg.Node):
    """Holds every input table's rows; re-evaluates ONE class arg's output
    attributes each epoch, emitting only changed rows."""

    # pointer walks cross arbitrary rows: centralize (reference runs row
    # transformers inside one worker's scope too)
    exchange_routes = cl.route_all_to_zero

    def __init__(self, graph, inputs, spec, target: str, name=None):
        super().__init__(graph, inputs, name or f"transformer_{spec.name}_{target}")
        self.spec = spec
        self.target = target

    def make_state(self):
        return {
            "rows": {name: {} for name in self.spec.class_args},
            "out": {},
        }

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        changed = False
        for (name, _cls), batch in zip(self.spec.class_args.items(), inbatches):
            rows = st["rows"][name]
            for u in batch:
                changed = True
                if u.diff > 0:
                    rows[u.key] = u.values
                else:
                    rows.pop(u.key, None)
        if not changed:
            return []
        runtime = _Runtime(self.spec, st["rows"])
        cls = self.spec.class_args[self.target]
        out: list[Update] = []
        new_out: dict[Any, tuple] = {}
        for key in st["rows"][self.target]:
            vals = []
            ok = True
            for oa in cls._output_attrs:
                try:
                    vals.append(runtime._resolve(self.target, key, oa.name))
                except Exception as e:  # noqa: BLE001 — contained per row
                    ctx.log_error(self, f"{self.name}[{key!r}].{oa.name}: {e!r}")
                    ok = False
                    break
            if not ok:
                continue
            for m in cls._methods:
                vals.append(
                    _BoundMethod(
                        self.spec, st["rows"], self.target, key, m.name
                    )
                )
            new_out[key] = tuple(vals)
        for key, old in st["out"].items():
            if key not in new_out:
                out.append(Update(key, old, -1))
            elif new_out[key] != old:
                out.append(Update(key, old, -1))
                out.append(Update(key, new_out[key], 1))
        for key, vals in new_out.items():
            if key not in st["out"]:
                out.append(Update(key, vals, 1))
        st["out"] = new_out
        return consolidate(out)


class _TransformerResult:
    def __init__(self, tables: dict[str, Any]):
        for name, t in tables.items():
            setattr(self, name, t)


class RowTransformer:
    def __init__(self, name: str, class_args: dict[str, type]):
        self.name = name
        self.class_args = class_args

    def __call__(self, **tables: Any) -> _TransformerResult:
        from pathway_tpu.internals.table import Table

        missing = set(self.class_args) - set(tables)
        if missing:
            raise TypeError(f"transformer {self.name} missing tables: {missing}")
        input_nodes = [tables[name]._node for name in self.class_args]
        outs: dict[str, Table] = {}
        for target, cls in self.class_args.items():
            node = _RowTransformerNode(
                G.engine_graph, input_nodes, self, target
            )
            cols = [oa.name for oa in cls._output_attrs] + [
                m.name for m in cls._methods
            ]
            dtypes = {c: dt.ANY for c in cols}
            outs[target] = Table(
                node, cols, dtypes, name=f"{self.name}.{target}"
            )
        return _TransformerResult(outs)


def transformer(cls: type) -> RowTransformer:
    """``@pw.transformer`` — turn a class of ``ClassArg`` inner classes
    into a callable row transformer."""
    class_args = {
        name: v
        for name, v in vars(cls).items()
        if isinstance(v, type) and issubclass(v, ClassArg)
    }
    if not class_args:
        raise TypeError(
            f"@pw.transformer class {cls.__name__} defines no ClassArg tables"
        )
    return RowTransformer(cls.__name__, class_args)
