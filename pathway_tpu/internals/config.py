"""Runtime configuration from environment variables.

Reference: ``python/pathway/internals/config.py:10-144`` +
``src/engine/dataflow/config.rs:86-120`` (worker topology env).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


@dataclass
class PathwayConfig:
    ignore_asserts: bool = field(default_factory=lambda: _env_bool("PATHWAY_IGNORE_ASSERTS"))
    runtime_typechecking: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_RUNTIME_TYPECHECKING")
    )
    persistent_storage: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_PERSISTENT_STORAGE")
    )
    threads: int = field(default_factory=lambda: _env_int("PATHWAY_THREADS", 1))
    processes: int = field(default_factory=lambda: _env_int("PATHWAY_PROCESSES", 1))
    process_id: int = field(default_factory=lambda: _env_int("PATHWAY_PROCESS_ID", 0))
    first_port: int = field(default_factory=lambda: _env_int("PATHWAY_FIRST_PORT", 10000))
    monitoring_http_port: int | None = field(
        default_factory=lambda: (
            int(p) if (p := os.environ.get("PATHWAY_MONITORING_HTTP_PORT")) else None
        )
    )
    license_key: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_LICENSE_KEY")
    )
    persistence_config: Any = None

    @property
    def total_workers(self) -> int:
        return self.threads * self.processes

    def refresh(self) -> None:
        self.__init__()


pathway_config = PathwayConfig()


def set_license_key(key: str | None) -> None:
    pathway_config.license_key = key


def set_monitoring_config(*, server_endpoint: str | None = None, **kwargs: Any) -> None:
    pathway_config.monitoring_endpoint = server_endpoint  # type: ignore[attr-defined]
    # the endpoint also drives the OTLP span/metric exporter
    from pathway_tpu.internals import telemetry

    telemetry.set_monitoring_config(server_endpoint=server_endpoint)
