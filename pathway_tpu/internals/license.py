"""License keys and entitlements (reference ``src/engine/license.rs``:
Ed25519-signed keys / offline license files, entitlement checks, free-tier
worker cap; ``license.rs:23-60``).

Same capability, fully offline: a license key is
``base64(payload_json) + "." + base64(ed25519_signature)`` verified
against the distribution public key (override with
``PATHWAY_LICENSE_PUBLIC_KEY`` — PEM — for self-issued deployments; the
reference instead phones ``license.pathway.com``, which this build never
does).  The payload carries the tier and entitlement list::

    {"tier": "scale", "entitlements": ["scale", "xpack-sharepoint"]}

No key (or the demo key) = free tier: everything works, workers cap at
:data:`MAX_WORKERS_FREE` like the reference
(``src/engine/dataflow/config.rs:7-11``).
"""

from __future__ import annotations

import base64
import binascii
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any

_logger = logging.getLogger("pathway_tpu.license")

__all__ = [
    "License",
    "LicenseError",
    "MAX_WORKERS_FREE",
    "check_entitlements",
    "generate_license_key",
    "get_license",
]

#: free-tier worker cap (reference MAX_WORKERS, config.rs:7-11)
MAX_WORKERS_FREE = 8

#: demo keys accepted verbatim (reference KEY_FOR_TELEMETRY-style demos)
_DEMO_KEYS = {"demo-license-key-with-telemetry", "demo"}

#: distribution public key (Ed25519, PEM).  Deployments that issue their
#: own licenses override via PATHWAY_LICENSE_PUBLIC_KEY.
_DEFAULT_PUBLIC_KEY_PEM = """-----BEGIN PUBLIC KEY-----
MCowBQYDK2VwAyEAvdMDRRaYVc7J0P5mRWMhKyUv2zvBTH4ZO0uFVUhmZi0=
-----END PUBLIC KEY-----"""


class LicenseError(ValueError):
    """Malformed, forged, or insufficient license."""


@dataclass(frozen=True)
class License:
    tier: str = "free"
    entitlements: tuple[str, ...] = ()
    telemetry: bool = False
    payload: dict = field(default_factory=dict)

    @property
    def scale_unlimited(self) -> bool:
        return "scale" in self.entitlements or "scale-unlimited" in self.entitlements

    def worker_cap(self) -> int | None:
        """None = unlimited."""
        return None if self.scale_unlimited else MAX_WORKERS_FREE

    def check_entitlements(self, *required: str) -> None:
        missing = [e for e in required if e not in self.entitlements]
        if missing:
            raise LicenseError(
                f"license (tier {self.tier!r}) is missing entitlement(s) "
                f"{missing}; set a key with pw.set_license_key(...)"
            )


def _public_key():
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import load_pem_public_key

    pem = os.environ.get("PATHWAY_LICENSE_PUBLIC_KEY", _DEFAULT_PUBLIC_KEY_PEM)
    try:
        pk = load_pem_public_key(pem.encode())
    except Exception as e:  # malformed PEM -> the documented error type
        raise LicenseError(f"invalid license public key: {e}") from None
    if not isinstance(pk, Ed25519PublicKey):
        raise LicenseError("license public key must be Ed25519")
    return pk


def parse_license(key: str | None) -> License:
    """Validate a key and return the License (free tier for no key)."""
    if not key:
        return License()
    key = key.strip()
    if key.lower() in _DEMO_KEYS:
        # demo keys unlock licensed xpacks for offline evaluation (but not
        # the worker cap), like the reference's telemetry demo keys
        return License(
            tier="demo", telemetry=True, entitlements=("xpack-sharepoint",)
        )
    try:
        payload_b64, sig_b64 = key.split(".", 1)
        payload_bytes = base64.urlsafe_b64decode(payload_b64 + "===")
        signature = base64.urlsafe_b64decode(sig_b64 + "===")
    except (ValueError, binascii.Error) as e:
        raise LicenseError(f"malformed license key: {e}") from None
    from cryptography.exceptions import InvalidSignature

    try:
        _public_key().verify(signature, payload_bytes)
    except InvalidSignature:
        raise LicenseError("license key signature is invalid") from None
    try:
        payload = json.loads(payload_bytes)
    except ValueError as e:
        raise LicenseError(f"license payload is not JSON: {e}") from None
    return License(
        tier=str(payload.get("tier", "licensed")),
        entitlements=tuple(payload.get("entitlements", ())),
        telemetry=bool(payload.get("telemetry", False)),
        payload=payload,
    )


def generate_license_key(payload: dict, private_key_pem: bytes | str) -> str:
    """Issue a key for a self-managed deployment (pair with
    ``PATHWAY_LICENSE_PUBLIC_KEY``); also the test-suite hook."""
    from cryptography.hazmat.primitives.serialization import (
        load_pem_private_key,
    )

    if isinstance(private_key_pem, str):
        private_key_pem = private_key_pem.encode()
    sk = load_pem_private_key(private_key_pem, password=None)
    payload_bytes = json.dumps(payload, sort_keys=True).encode()
    sig = sk.sign(payload_bytes)
    return (
        base64.urlsafe_b64encode(payload_bytes).decode().rstrip("=")
        + "."
        + base64.urlsafe_b64encode(sig).decode().rstrip("=")
    )


_cache: dict[tuple[str, str], License] = {}


def get_license() -> License:
    """The validated license for the current config key (cached per
    (key, public key), so rotating PATHWAY_LICENSE_PUBLIC_KEY
    re-verifies)."""
    from pathway_tpu.internals.config import pathway_config

    key = pathway_config.license_key or ""
    pub = os.environ.get("PATHWAY_LICENSE_PUBLIC_KEY", "")
    lic = _cache.get((key, pub))
    if lic is None:
        lic = parse_license(key)
        _cache[(key, pub)] = lic
    return lic


def check_entitlements(*required: str) -> None:
    """Entitlement gate for licensed features (reference
    ``license.rs`` entitlement checks; wired into e.g. the SharePoint
    xpack connector)."""
    get_license().check_entitlements(*required)


def effective_workers(requested: int) -> int:
    """Clamp a requested worker count to the license cap, warning like the
    reference free tier does."""
    cap = get_license().worker_cap()
    if cap is not None and requested > cap:
        _logger.warning(
            "free tier caps workers at %d (requested %d); set a license "
            "key with the 'scale' entitlement to lift the cap",
            cap,
            requested,
        )
        return cap
    return requested
