"""Global graph holder ``G``.

Reference: ``python/pathway/internals/parse_graph.py`` keeps a global
``ParseGraph`` rebuilt per test.  Here the user API constructs engine nodes
eagerly (no separate replay layer is needed because nodes are stateless
descriptions — execution state lives in a per-run ``RunContext``), so ``G``
holds the single :class:`EngineGraph` plus the error log and run bookkeeping.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from pathway_tpu.engine.graph import EngineGraph

logger = logging.getLogger("pathway_tpu")


class ParseGraph:
    def __init__(self) -> None:
        self.engine_graph = EngineGraph()
        self.errors: list[str] = []
        self.last_run_ctx: Any = None
        self._cache: dict[Any, Any] = {}
        #: lazily created global error-log table (pw.global_error_log)
        self.error_log_table: Any = None

    def clear(self) -> None:
        self.__init__()

    def log_error(self, message: str, trace: str = "") -> None:
        self.errors.append(message)
        logger.warning(
            "pathway_tpu error value produced: %s%s",
            message,
            f" [at {trace}]" if trace else "",
        )
        # runtime (per-cell) errors also feed the global error-log table
        # of the run that produced them
        from pathway_tpu.engine.graph import ErrorEntry, current_ctx

        ctx = current_ctx()
        if ctx is not None:
            entry = ErrorEntry(message, trace=trace, time=ctx.time)
            ctx.error_log.append(entry)
            if ctx.error_sink_enabled:
                ctx.error_pending.append(entry)


G = ParseGraph()


def global_error_log() -> Any:
    """The queryable global error-log Table (reference
    ``pw.global_error_log``, ``internals/parse_graph.py:183-202``): rows
    ``(message, operator, trace)`` — ``trace`` is the user file:line that
    created the failing operator.  Compose it like any table (filter,
    output, subscribe)."""
    if G.error_log_table is None:
        from pathway_tpu.engine import graph as eg
        from pathway_tpu.internals import dtype as dt
        from pathway_tpu.internals.table import Table

        node = eg.ErrorLogNode(G.engine_graph)
        G.error_log_table = Table(
            node,
            ["message", "operator", "trace"],
            {"message": dt.STR, "operator": dt.STR, "trace": dt.STR},
            name="global_error_log",
        )
    return G.error_log_table
