"""Global graph holder ``G``.

Reference: ``python/pathway/internals/parse_graph.py`` keeps a global
``ParseGraph`` rebuilt per test.  Here the user API constructs engine nodes
eagerly (no separate replay layer is needed because nodes are stateless
descriptions — execution state lives in a per-run ``RunContext``), so ``G``
holds the single :class:`EngineGraph` plus the error log and run bookkeeping.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from pathway_tpu.engine.graph import EngineGraph

logger = logging.getLogger("pathway_tpu")


class ParseGraph:
    def __init__(self) -> None:
        self.engine_graph = EngineGraph()
        self.errors: list[str] = []
        self.last_run_ctx: Any = None
        self._cache: dict[Any, Any] = {}

    def clear(self) -> None:
        self.__init__()

    def log_error(self, message: str) -> None:
        self.errors.append(message)
        logger.warning("pathway_tpu error value produced: %s", message)


G = ParseGraph()
