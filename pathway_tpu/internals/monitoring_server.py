"""Per-process monitoring HTTP endpoint (reference
``src/engine/http_server.rs:21-130``): ``/status``, OpenMetrics
``/metrics``, ``/debug/stacks``, and ``/debug/trace?seconds=N`` on port
``PATHWAY_MONITORING_HTTP_PORT`` (default 20000) + process id."""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

__all__ = ["start_http_server"]


def _metrics_text(sched: Any) -> str:
    ctx = sched.ctx
    lines = [
        "# TYPE pathway_tpu_epoch gauge",
        f"pathway_tpu_epoch {ctx.time}",
        "# TYPE pathway_tpu_error_count gauge",
        f"pathway_tpu_error_count {len(ctx.error_log)}",
        "# TYPE pathway_tpu_operator_count gauge",
        f"pathway_tpu_operator_count {len(sched.graph.nodes)}",
    ]
    # per-connector counters (reference src/connectors/monitoring.rs);
    # copied under the scheduler's lock (registration races iteration)
    connector_stats = sched.snapshot_connector_stats()
    if connector_stats:
        lines.append("# TYPE pathway_tpu_connector_rows_total counter")
        lines.append("# TYPE pathway_tpu_connector_commits_total counter")
        lines.append("# TYPE pathway_tpu_connector_restarts_total counter")
        lines.append("# TYPE pathway_tpu_connector_failures_total counter")
        lines.append("# TYPE pathway_tpu_connector_stale gauge")
        for name, c in sorted(connector_stats.items()):
            label = name.replace('"', "'")
            lines.append(
                f'pathway_tpu_connector_rows_total{{input="{label}"}} '
                f"{c.get('rows', 0)}"
            )
            lines.append(
                f'pathway_tpu_connector_commits_total{{input="{label}"}} '
                f"{c.get('commits', 0)}"
            )
            lines.append(
                f'pathway_tpu_connector_restarts_total{{input="{label}"}} '
                f"{c.get('restarts', 0)}"
            )
            lines.append(
                f'pathway_tpu_connector_failures_total{{input="{label}"}} '
                f"{c.get('failures', 0)}"
            )
            lines.append(
                f'pathway_tpu_connector_stale{{input="{label}"}} '
                f"{1 if c.get('stale') else 0}"
            )
    # resilience counters (supervisor restarts, breaker trips, DLQ)
    from pathway_tpu.internals.telemetry import get_telemetry

    for name, v in sorted(get_telemetry().snapshot_counters().items()):
        metric = "pathway_tpu_" + name.replace(".", "_") + "_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {v}")
    # columnar vs row execution-path row counts (ISSUE 19): a pipeline
    # silently degraded to the row fallback shows up as path="row"
    # dominating instead of a latent slowdown
    colrows = ctx.stats.get("columnar_rows")
    if colrows:
        lines.append("# TYPE pathway_tpu_columnar_rows_total counter")
        for path in ("columnar", "row"):
            lines.append(
                f'pathway_tpu_columnar_rows_total{{path="{path}"}} '
                f"{colrows.get(path, 0)}"
            )
    # per-operator probes (reference attach_prober, graph.rs:988-995)
    probes = ctx.stats.get("operators", {})
    if probes:
        lines.append("# TYPE pathway_tpu_operator_rows_in_total counter")
        lines.append("# TYPE pathway_tpu_operator_rows_out_total counter")
        lines.append("# TYPE pathway_tpu_operator_latency_ms_total counter")
        lines.append("# TYPE pathway_tpu_state_bytes gauge")
        for p in probes.values():
            label = p["name"].replace('"', "'")
            lines.append(
                f'pathway_tpu_operator_rows_in_total{{operator="{label}"}} '
                f"{p['rows_in']}"
            )
            lines.append(
                f'pathway_tpu_operator_rows_out_total{{operator="{label}"}} '
                f"{p['rows_out']}"
            )
            lines.append(
                f'pathway_tpu_operator_latency_ms_total{{operator="{label}"}} '
                f"{p['total_ms']:.3f}"
            )
            lines.append(
                f'pathway_tpu_state_bytes{{operator="{label}"}} '
                f"{p.get('state_bytes', 0)}"
            )
    # static capacity predictions next to the measured gauges above —
    # the cross-validation pair (analysis/memory.py); same operator label
    est = getattr(sched, "memory_estimate", None)
    if est is not None and getattr(est, "operators", None):
        lines.append("# TYPE pathway_tpu_state_bytes_estimated gauge")
        for o in est.operators:
            label = f"{o.name}#{o.node_id}".replace('"', "'")
            lines.append(
                f'pathway_tpu_state_bytes_estimated{{operator="{label}"}} '
                f"{o.total_bytes}"
            )
    # per-stage streaming latency histograms (ISSUE 4 tentpole c): the
    # scheduler's LatencyProbe reduced to quantile gauges per stage
    lat = _latency_snapshot(sched)
    if lat:
        lines.append("# TYPE pathway_tpu_stage_latency_ms gauge")
        lines.append("# TYPE pathway_tpu_stage_latency_count gauge")
        lines.append("# TYPE pathway_tpu_stage_latency_ms_count counter")
        lines.append("# TYPE pathway_tpu_stage_latency_ms_sum counter")
        for stage, d in sorted(lat.items()):
            for qk in ("p50", "p95", "p99", "max"):
                lines.append(
                    f'pathway_tpu_stage_latency_ms{{stage="{stage}",'
                    f'quantile="{qk}"}} {d[qk + "_ms"]:.4f}'
                )
            lines.append(
                f'pathway_tpu_stage_latency_count{{stage="{stage}"}} '
                f"{d['count']}"
            )
            # _count/_sum companions so rate(sum)/rate(count) gives the
            # true windowed mean (quantile gauges can't be averaged)
            lines.append(
                f'pathway_tpu_stage_latency_ms_count{{stage="{stage}"}} '
                f"{d['count']}"
            )
            lines.append(
                f'pathway_tpu_stage_latency_ms_sum{{stage="{stage}"}} '
                f"{d.get('sum_ms', 0.0):.4f}"
            )
    # pre-flight static-analyzer finding counts (pathway_tpu/analysis/)
    findings = getattr(sched, "analysis_findings", {}) or {}
    if findings:
        lines.append("# TYPE pathway_tpu_analysis_findings gauge")
        for sev, n in sorted(findings.items()):
            lines.append(
                f'pathway_tpu_analysis_findings{{severity="{sev}"}} {n}'
            )
    # plan-compiler rewrite counters (analysis/rewrite.py), one gauge
    # per applied pass, plus the effective optimization level
    plan_counters = getattr(sched, "plan_counters", {}) or {}
    if plan_counters:
        lines.append("# TYPE pathway_tpu_plan_rewrites gauge")
        for pass_name, n in sorted(plan_counters.items()):
            lines.append(
                f'pathway_tpu_plan_rewrites{{pass="{pass_name}"}} {n}'
            )
    plan = getattr(sched, "execution_plan", None)
    if plan is not None:
        lines.append("# TYPE pathway_tpu_plan_level gauge")
        lines.append(f"pathway_tpu_plan_level {plan.level}")
    # coordinated-checkpoint health (fault-tolerance observability): a
    # growing age with bytes stuck means checkpoints stopped landing —
    # the alert that matters before a worker ever dies
    ckpt = _checkpoint_snapshot(sched)
    if ckpt:
        age = ckpt.get("age_seconds")
        lines.append("# TYPE pathway_tpu_checkpoint_age_seconds gauge")
        lines.append(
            f"pathway_tpu_checkpoint_age_seconds "
            f"{age if age is not None else -1:.3f}"
        )
        lines.append("# TYPE pathway_tpu_checkpoint_bytes gauge")
        lines.append(f"pathway_tpu_checkpoint_bytes {ckpt.get('bytes', 0)}")
    # live index maintenance (delta segment / tombstones / merges per
    # external-index operator; see stdlib/indexing/segments.py) — the
    # gauges that show churn outrunning the background merge
    idx = _index_snapshot(sched)
    if idx:
        lines.append("# TYPE pathway_tpu_index_size gauge")
        lines.append("# TYPE pathway_tpu_index_delta_size gauge")
        lines.append("# TYPE pathway_tpu_index_tombstones gauge")
        lines.append("# TYPE pathway_tpu_index_merges_total counter")
        for name, s in sorted(idx.items()):
            label = name.replace('"', "'")
            lines.append(
                f'pathway_tpu_index_size{{index="{label}"}} '
                f"{s.get('size', 0)}"
            )
            lines.append(
                f'pathway_tpu_index_delta_size{{index="{label}"}} '
                f"{s.get('delta_size', 0)}"
            )
            lines.append(
                f'pathway_tpu_index_tombstones{{index="{label}"}} '
                f"{s.get('tombstones', 0)}"
            )
            lines.append(
                f'pathway_tpu_index_merges_total{{index="{label}"}} '
                f"{s.get('merges_total', 0)}"
            )
    lines.append("# TYPE pathway_tpu_worker_restarts_total counter")
    lines.append(
        f"pathway_tpu_worker_restarts_total "
        f"{int(getattr(sched, 'worker_restarts', 0) or 0)}"
    )
    # multi-tenant serving layer (admission + SLO scheduling, ISSUE 10):
    # admitted/shed counters per tenant class, and the serving stages'
    # latency quantiles carrying the tenant_class label.  The engine
    # stage lines above stay label-free — serving emits ADDITIONAL
    # labeled series, so existing dashboards keep parsing.
    srv = _serving_snapshot()
    adm = srv.get("admission", {})
    if adm:
        lines.append("# TYPE pathway_tpu_serving_admitted_total counter")
        lines.append("# TYPE pathway_tpu_serving_shed_total counter")
        lines.append("# TYPE pathway_tpu_serving_inflight gauge")
        for cls, n in sorted(adm.get("admitted_total", {}).items()):
            label = str(cls).replace('"', "'")
            lines.append(
                f'pathway_tpu_serving_admitted_total{{tenant_class="{label}"}} {n}'
            )
        for cls, n in sorted(adm.get("shed_total", {}).items()):
            label = str(cls).replace('"', "'")
            lines.append(
                f'pathway_tpu_serving_shed_total{{tenant_class="{label}"}} {n}'
            )
        for cls, n in sorted(adm.get("inflight", {}).items()):
            label = str(cls).replace('"', "'")
            lines.append(
                f'pathway_tpu_serving_inflight{{tenant_class="{label}"}} {n}'
            )
    srv_lat = srv.get("latency", {})
    if srv_lat:
        lines.append("# TYPE pathway_tpu_stage_latency_ms gauge")
        lines.append("# TYPE pathway_tpu_stage_latency_count gauge")
        lines.append("# TYPE pathway_tpu_stage_latency_ms_count counter")
        lines.append("# TYPE pathway_tpu_stage_latency_ms_sum counter")
        for stage, by_class in sorted(srv_lat.items()):
            for cls, d in sorted(by_class.items()):
                label = str(cls).replace('"', "'")
                for qk in ("p50", "p95", "p99", "max"):
                    lines.append(
                        f'pathway_tpu_stage_latency_ms{{stage="{stage}",'
                        f'tenant_class="{label}",quantile="{qk}"}} '
                        f"{d[qk + '_ms']:.4f}"
                    )
                lines.append(
                    f'pathway_tpu_stage_latency_count{{stage="{stage}",'
                    f'tenant_class="{label}"}} {d["count"]}'
                )
                lines.append(
                    f'pathway_tpu_stage_latency_ms_count{{stage="{stage}",'
                    f'tenant_class="{label}"}} {d["count"]}'
                )
                lines.append(
                    f'pathway_tpu_stage_latency_ms_sum{{stage="{stage}",'
                    f'tenant_class="{label}"}} {d.get("sum_ms", 0.0):.4f}'
                )
    # degraded serving / shard failover (ISSUE 13): shard health, responses
    # served with partial coverage, and the failover-duration histogram —
    # the dashboard panel for "one owner died; did anyone notice?"
    fo = srv.get("failover", {})
    if fo:
        lines.append("# TYPE pathway_tpu_shards_total gauge")
        lines.append(f"pathway_tpu_shards_total {fo.get('shards_total', 0)}")
        lines.append("# TYPE pathway_tpu_shards_healthy gauge")
        lines.append(
            f"pathway_tpu_shards_healthy {fo.get('shards_healthy', 0)}"
        )
        lines.append("# TYPE pathway_tpu_degraded_responses_total counter")
        lines.append(
            f"pathway_tpu_degraded_responses_total "
            f"{fo.get('degraded_responses_total', 0)}"
        )
        lines.append("# TYPE pathway_tpu_failovers_total counter")
        lines.append(
            f"pathway_tpu_failovers_total {fo.get('failovers_total', 0)}"
        )
        hist = fo.get("failover_seconds") or {}
        if hist.get("count"):
            lines.append("# TYPE pathway_tpu_failover_seconds gauge")
            for qk in ("p50", "p95", "p99", "max"):
                lines.append(
                    f'pathway_tpu_failover_seconds{{quantile="{qk}"}} '
                    f"{hist.get(qk + '_ns', 0) / 1e9:.6f}"
                )
            lines.append("# TYPE pathway_tpu_failover_seconds_count counter")
            lines.append(
                f"pathway_tpu_failover_seconds_count {hist.get('count', 0)}"
            )
            lines.append("# TYPE pathway_tpu_failover_seconds_sum counter")
            lines.append(
                f"pathway_tpu_failover_seconds_sum "
                f"{hist.get('sum_ns', 0) / 1e9:.6f}"
            )
    # backpressure (ISSUE 16): bounded ingest buffer occupancy per source,
    # exchange credit backlog per peer, brownout level + sheds — the
    # panels that explain "slow but alive" before it becomes an OOM
    pressure = _pressure_snapshot(sched)
    ing = pressure.get("ingest", {})
    if ing:
        tot = ing.get("totals", {})
        lines.append("# TYPE pathway_tpu_ingest_buffer_capacity_bytes gauge")
        lines.append(
            f"pathway_tpu_ingest_buffer_capacity_bytes "
            f"{tot.get('capacity_bytes', 0)}"
        )
        lines.append("# TYPE pathway_tpu_ingest_credit_stalls_total counter")
        lines.append(
            f"pathway_tpu_ingest_credit_stalls_total "
            f"{tot.get('stalls_total', 0)}"
        )
        srcs = ing.get("sources", {})
        if srcs:
            lines.append("# TYPE pathway_tpu_ingest_queue_rows gauge")
            lines.append("# TYPE pathway_tpu_ingest_queue_bytes gauge")
            lines.append("# TYPE pathway_tpu_ingest_shed_rows_total counter")
            lines.append("# TYPE pathway_tpu_ingest_paused gauge")
            for name, s in sorted(srcs.items()):
                label = str(name).replace('"', "'")
                lines.append(
                    f'pathway_tpu_ingest_queue_rows{{input="{label}"}} '
                    f"{s.get('rows', 0)}"
                )
                lines.append(
                    f'pathway_tpu_ingest_queue_bytes{{input="{label}"}} '
                    f"{s.get('bytes', 0)}"
                )
                lines.append(
                    f'pathway_tpu_ingest_shed_rows_total{{input="{label}"}} '
                    f"{s.get('shed_rows', 0)}"
                )
                lines.append(
                    f'pathway_tpu_ingest_paused{{input="{label}"}} '
                    f"{1 if s.get('paused') else 0}"
                )
    ex = pressure.get("exchange", {})
    if ex:
        lines.append("# TYPE pathway_tpu_exchange_credit_bytes gauge")
        lines.append(
            f"pathway_tpu_exchange_credit_bytes {ex.get('credit_bytes', 0)}"
        )
        lines.append("# TYPE pathway_tpu_exchange_credit_stalls_total counter")
        lines.append(
            f"pathway_tpu_exchange_credit_stalls_total "
            f"{ex.get('credit_stalls_total', 0)}"
        )
        peers = ex.get("peers", {})
        if peers:
            lines.append("# TYPE pathway_tpu_exchange_backlog_bytes gauge")
            for p, s in sorted(peers.items()):
                lines.append(
                    f'pathway_tpu_exchange_backlog_bytes{{peer="{p}"}} '
                    f"{s.get('backlog_bytes', 0)}"
                )
    srv_p = pressure.get("serving", {})
    if srv_p:
        lines.append("# TYPE pathway_tpu_serving_brownout_level gauge")
        lines.append(
            f"pathway_tpu_serving_brownout_level "
            f"{srv_p.get('pressure_level', 0.0):.4f}"
        )
        bshed = srv_p.get("brownout_shed_total", {})
        if bshed:
            lines.append(
                "# TYPE pathway_tpu_serving_brownout_shed_total counter"
            )
            for cls, n in sorted(bshed.items()):
                label = str(cls).replace('"', "'")
                lines.append(
                    f"pathway_tpu_serving_brownout_shed_total"
                    f'{{tenant_class="{label}"}} {n}'
                )
    device = _device_snapshot()
    ctr = device.get("counters", {})
    if ctr:
        lines.append("# TYPE pathway_tpu_jit_compiles_total counter")
        lines.append(
            f"pathway_tpu_jit_compiles_total {ctr.get('jit_compiles', 0)}"
        )
        lines.append("# TYPE pathway_tpu_h2d_bytes_total counter")
        lines.append(f"pathway_tpu_h2d_bytes_total {ctr.get('h2d_bytes', 0)}")
        lines.append("# TYPE pathway_tpu_d2h_bytes_total counter")
        lines.append(f"pathway_tpu_d2h_bytes_total {ctr.get('d2h_bytes', 0)}")
        lines.append("# TYPE pathway_tpu_h2d_transfers_total counter")
        lines.append(
            f"pathway_tpu_h2d_transfers_total {ctr.get('h2d_transfers', 0)}"
        )
        lines.append("# TYPE pathway_tpu_d2h_transfers_total counter")
        lines.append(
            f"pathway_tpu_d2h_transfers_total {ctr.get('d2h_transfers', 0)}"
        )
        static = device.get("static", {})
        if static:
            lines.append(
                "# TYPE pathway_tpu_device_predicted_recompile_sites gauge"
            )
            lines.append(
                f"pathway_tpu_device_predicted_recompile_sites "
                f"{static.get('predicted_recompile_sites', 0)}"
            )
    return "\n".join(lines) + "\n# EOF\n"


def _latency_snapshot(sched: Any) -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import latency_stats

    return latency_stats(sched)


def _checkpoint_snapshot(sched: Any) -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import checkpoint_stats

    return checkpoint_stats(sched)


def _index_snapshot(sched: Any) -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import index_stats

    return index_stats(sched)


def _serving_snapshot() -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import serving_stats

    return serving_stats()


def _memory_snapshot(sched: Any) -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import memory_stats

    return memory_stats(sched)


def _pressure_snapshot(sched: Any) -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import pressure_stats

    return pressure_stats(sched)


def _device_snapshot() -> dict[str, Any]:
    from pathway_tpu.internals.monitoring import device_stats

    return device_stats()


def start_http_server(sched: Any, port: int | None = None) -> threading.Thread:
    if port is None:
        base = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
        port = base + int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802
            if self.path.startswith("/status"):
                srv = _serving_snapshot()
                fo = srv.get("failover", {})
                xplan = getattr(sched, "execution_plan", None)
                body = json.dumps(
                    {
                        "epoch": sched.ctx.time,
                        "operators": len(sched.graph.nodes),
                        "errors": len(sched.ctx.error_log),
                        "latency": _latency_snapshot(sched),
                        # pre-flight analyzer verdict for the running graph
                        "analysis": dict(
                            getattr(sched, "analysis_findings", {}) or {}
                        ),
                        # plan-compiler rewrite counters + level, plus
                        # the per-operator columnar/row path decisions
                        # and the runtime rows-per-path counter
                        "plan": {
                            "level": getattr(xplan, "level", 0),
                            "rewrites": dict(
                                getattr(sched, "plan_counters", {}) or {}
                            ),
                            "columnar": (
                                xplan.columnar_lines()
                                if hasattr(xplan, "columnar_lines")
                                else []
                            ),
                            "columnar_rows": dict(
                                sched.ctx.stats.get("columnar_rows", {})
                            ),
                        },
                        # coordinated-checkpoint health: last checkpoint
                        # epoch, its age/size, and the supervisor restart
                        # generation ({} when persistence is off)
                        "checkpoint": _checkpoint_snapshot(sched),
                        # live index maintenance per index operator:
                        # delta/tombstones/merges (segments.py)
                        "index": _index_snapshot(sched),
                        # capacity cross-validation: statically estimated
                        # vs runtime-sampled state bytes per operator
                        # (analysis/memory.py + scheduler sampling)
                        "memory": _memory_snapshot(sched),
                        # multi-tenant serving layer: admission counters
                        # per tenant class, scheduler lane stats, and
                        # per-(stage, tenant_class) latency (ISSUE 10)
                        "serving": srv,
                        # backpressure across the bounded hops: ingest
                        # buffer, exchange credit windows, brownout
                        # (ISSUE 16)
                        "pressure": _pressure_snapshot(sched),
                        # device-plane join: live jit-compile + H2D/D2H
                        # counters next to the static device-safety
                        # prediction (analysis/device.py); a warmed
                        # serving loop must hold jit_compiles flat
                        "device": _device_snapshot(),
                        # degraded-mode summary (ISSUE 13): one glance says
                        # whether answers are currently partial and why
                        "degraded": {
                            "active": fo.get("shards_healthy", 0)
                            < fo.get("shards_total", 0),
                            "shards_healthy": fo.get("shards_healthy", 0),
                            "shards_total": fo.get("shards_total", 0),
                            "degraded_responses_total": fo.get(
                                "degraded_responses_total", 0
                            ),
                            "failovers_total": fo.get("failovers_total", 0),
                        }
                        if fo
                        else {},
                    }
                ).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics"):
                body = _metrics_text(sched).encode()
                ctype = "application/openmetrics-text"
            elif self.path.startswith("/debug/stacks"):
                from pathway_tpu.internals import tracing

                body = tracing.dump_stacks().encode()
                ctype = "text/plain"
            elif self.path.startswith("/debug/trace"):
                import time as _time
                from urllib.parse import parse_qs, urlsplit

                from pathway_tpu.internals import tracing

                qs = parse_qs(urlsplit(self.path).query)
                since_ns = None
                try:
                    secs = float(qs["seconds"][0])
                    since_ns = _time.monotonic_ns() - int(secs * 1e9)
                except (KeyError, IndexError, ValueError):
                    pass
                body = json.dumps(
                    {
                        "traceEvents": tracing.chrome_events(
                            since_ns=since_ns, all_spans=True
                        )
                    }
                ).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args: Any) -> None:
            pass

    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True, name="pw_monitoring")
    t.start()
    sched._monitoring_server = server
    # SIGUSR2 → dump all thread stacks to stderr and flush the tracing
    # flight recorder to PATHWAY_TRACE_DIR (no-op off the main thread)
    from pathway_tpu.internals import tracing

    tracing.install_sigusr2()
    return t
