"""Live device-plane counters: jit compiles and host<->device bytes.

The static device analyzer (``pathway_tpu/analysis/device.py``) PREDICTS
where recompiles and transfers happen; this module MEASURES them, the
same estimated-vs-measured join PR 15 gave memory capacity.  Three
counters, all monotonic:

- ``jit_compiles`` — one per actual XLA backend compile, observed via
  ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
  event (cache hits emit nothing, so a warmed, shape-stable serving loop
  holds this flat — the zero-recompile steady-state invariant the bench
  ``--smoke`` gate enforces).
- ``h2d_bytes`` / ``d2h_bytes`` — recorded at the repo's own transfer
  call sites (``parallel/sharded_knn.py`` dispatch/collect,
  ``parallel/executor.py`` chunk uploads/readbacks, ``parallel/
  ivf_knn.py``); jax has no public per-transfer hook, so these count the
  transfers *we* issue, which is exactly the set the analyzer reasons
  about.

Exported as ``pathway_tpu_jit_compiles_total`` /
``pathway_tpu_h2d_bytes_total`` / ``pathway_tpu_d2h_bytes_total`` on
``/metrics`` and joined against the static prediction on ``/status``.
Importing this module never imports jax; ``install()`` is called lazily
by the first transfer-recording caller (all of which already have jax
loaded) and degrades to transfer-only counting when ``jax.monitoring``
is unavailable.
"""

from __future__ import annotations

import threading
from typing import Any

__all__ = [
    "install",
    "installed",
    "record_h2d",
    "record_d2h",
    "snapshot",
    "compile_count",
    "reset_for_tests",
]

_lock = threading.Lock()
_installed = False
_install_failed = False

# monotonic counters; ints under the GIL, guarded anyway for += races
_counters: dict[str, int] = {
    "jit_compiles": 0,
    "h2d_bytes": 0,
    "h2d_transfers": 0,
    "d2h_bytes": 0,
    "d2h_transfers": 0,
}


def _bump(key: str, amount: int) -> None:
    with _lock:
        _counters[key] += amount


def _on_duration(event: str, duration: float, **kw: Any) -> None:
    # one backend_compile_duration per actual XLA compile; the sibling
    # jaxpr_trace / jaxpr_to_mlir events fire on cheap retraces too, so
    # only the backend event counts as "a compile happened"
    if event.endswith("backend_compile_duration"):
        _bump("jit_compiles", 1)


def install() -> bool:
    """Register the jit-compile listener (idempotent).  Returns whether
    compile counting is live; byte counters work either way."""
    global _installed, _install_failed
    if _installed:
        return True
    if _install_failed:
        return False
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            _install_failed = True
            return False
        _installed = True
    return True


def installed() -> bool:
    return _installed


def record_h2d(nbytes: int) -> None:
    """Count one host->device upload of ``nbytes`` (call at the repo's
    ``device_put``/np->jnp coercion sites)."""
    install()
    _bump("h2d_bytes", int(nbytes))
    _bump("h2d_transfers", 1)


def record_d2h(nbytes: int) -> None:
    """Count one device->host readback of ``nbytes``."""
    install()
    _bump("d2h_bytes", int(nbytes))
    _bump("d2h_transfers", 1)


def compile_count() -> int:
    """Current jit-compile total (installs the listener on first use so
    bench warmup loops can bracket themselves)."""
    install()
    return _counters["jit_compiles"]


def snapshot() -> dict[str, int]:
    """Point-in-time copy of all counters (for /metrics and /status)."""
    with _lock:
        out = dict(_counters)
    out["listener_installed"] = 1 if _installed else 0
    return out


def reset_for_tests() -> None:
    """Zero the counters (the jax listener cannot be unregistered, so
    tests bracket with deltas or reset)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0
