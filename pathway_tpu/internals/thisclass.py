"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

Reference: ``python/pathway/internals/thisclass.py``.  A placeholder stands
for a not-yet-known table inside expressions passed to ``select``/``filter``/
``join``; substitution happens when the expression is bound to an operation.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference


class ThisMetaclass(type):
    def __getattr__(cls, name: str) -> Any:
        # block python-internal probes but allow framework columns
        # (pw.this._pw_window_start etc.)
        if name.startswith("_") and not name.startswith("_pw_"):
            raise AttributeError(name)
        return ColumnReference(cls, name)

    def __getitem__(cls, name: str) -> Any:
        if isinstance(name, str):
            return ColumnReference(cls, name)
        raise TypeError(f"Cannot index placeholder with {name!r}")

    def __repr__(cls) -> str:
        return f"<pw.{cls.__name__}>"


class this(metaclass=ThisMetaclass):
    """The table the current operation applies to."""


class left(metaclass=ThisMetaclass):
    """Left side of a join."""


class right(metaclass=ThisMetaclass):
    """Right side of a join."""
