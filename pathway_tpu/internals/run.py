"""``pw.run`` — execute the constructed dataflow.

Reference: ``python/pathway/internals/run.py`` + ``GraphRunner``
(``internals/graph_runner/__init__.py:36-252``).  Runs the epoch scheduler
over the global graph; with live connectors it blocks until all sources
close (streaming mode), mirroring ``pw.run`` blocking semantics.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"
    AUTO = "auto"


def run(
    *,
    monitoring_level: Any = MonitoringLevel.AUTO,
    with_http_server: bool = False,
    autocommit_duration_ms: int | None = 50,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    **kwargs: Any,
):
    """Run the whole computation graph (blocking until sources finish)."""
    from pathway_tpu.internals import config as cfg

    if persistence_config is None:
        persistence_config = cfg.pathway_config.persistence_config
    pc = cfg.pathway_config
    saved_typecheck = pc.runtime_typechecking
    if runtime_typechecking is not None:
        pc.runtime_typechecking = runtime_typechecking
    try:
        return _run_inner(
            pc,
            monitoring_level,
            with_http_server,
            autocommit_duration_ms,
            persistence_config,
        )
    finally:
        # per-run override, not a process-wide setting
        pc.runtime_typechecking = saved_typecheck


def _run_inner(
    pc: Any,
    monitoring_level: Any,
    with_http_server: bool,
    autocommit_duration_ms: int | None,
    persistence_config: Any,
):
    from pathway_tpu.internals import config as cfg
    from pathway_tpu.internals.license import LicenseError, get_license

    threads = max(1, pc.threads)
    processes = max(1, pc.processes)
    # free tier caps total workers (reference MAX_WORKERS, config.rs:7-11).
    # Thread counts clamp locally; a process topology over the cap cannot
    # be shrunk from inside one process, so it is refused outright (every
    # process raises the same error).
    cap = get_license().worker_cap()
    if cap is not None and threads * processes > cap:
        if processes > cap:
            raise LicenseError(
                f"free tier allows at most {cap} workers but "
                f"PATHWAY_PROCESSES={processes}; set a license key with "
                "the 'scale' entitlement"
            )
        threads = max(1, cap // processes)
        import logging

        logging.getLogger("pathway_tpu.license").warning(
            "free tier caps workers at %d: running %d threads x %d "
            "processes = %d workers; set a license key with the 'scale' "
            "entitlement to lift the cap",
            cap,
            threads,
            processes,
            threads * processes,
        )
    sched = Scheduler(
        G.engine_graph,
        autocommit_ms=autocommit_duration_ms or 50,
    )
    if with_http_server or cfg.pathway_config.monitoring_http_port:
        from pathway_tpu.internals.monitoring_server import start_http_server

        start_http_server(sched)
    # live TUI dashboard (reference pw.run(monitoring_level=...) rich TUI):
    # AUTO shows it only on a real terminal; NONE never
    show = monitoring_level in (MonitoringLevel.ALL, MonitoringLevel.IN_OUT)
    if monitoring_level == MonitoringLevel.AUTO:
        import sys

        show = sys.stderr.isatty()
    if show:
        try:
            from pathway_tpu.internals.monitoring import start_dashboard

            start_dashboard(
                sched,
                level=(
                    monitoring_level
                    if monitoring_level != MonitoringLevel.AUTO
                    else MonitoringLevel.ALL
                ),
            )
        except ImportError:
            pass  # rich unavailable: run silently
    if persistence_config is not None:
        from pathway_tpu.persistence import attach_persistence

        attach_persistence(sched, persistence_config)
    G.active_scheduler = sched  # handle for stopping threaded servers
    from pathway_tpu.internals.telemetry import get_telemetry

    telemetry = get_telemetry()
    with telemetry.span(
        "graph_runner.run", operators=len(G.engine_graph.nodes)
    ), _ManagedGc():
        if threads * processes > 1:
            # multi-worker topology from the spawn env contract
            # (PATHWAY_THREADS × PATHWAY_PROCESSES, reference config.rs:86-120)
            from pathway_tpu.engine.cluster import Cluster

            cluster = Cluster(
                threads=threads,
                processes=processes,
                process_id=pc.process_id,
                first_port=pc.first_port,
            )
            try:
                ctx = sched.run_cluster(cluster)
            finally:
                cluster.close()
        else:
            ctx = sched.run()
    telemetry.record_process_metrics()
    telemetry.gauge("run.epoch", ctx.time)
    telemetry.gauge("run.errors", len(ctx.error_log))
    telemetry.export_metrics()
    G.last_run_ctx = ctx
    return ctx


class _ManagedGc:
    """Collector discipline for the run hot loop.

    CPython's automatic gen-0 collection fires every ~700 net container
    allocations; a streaming epoch allocates millions of short-lived row
    tuples, so the collector (plus the per-collection XLA gc callback JAX
    registers) costs ~2x wordcount throughput (measured: 183k -> 380k
    rows/s on the 400k-line benchmark).  The reference engine has no such
    pauses — Rust frees rows deterministically (src/engine/dataflow.rs) —
    so the TPU build's host runtime disables *automatic* collection for
    the duration of the run and sweeps young generations from a timed
    caretaker thread instead: cycle garbage stays bounded, with no
    per-allocation pauses.  Plain reference-counted garbage (the vast
    majority of row data) is unaffected — it is freed immediately either
    way.  Opt out with PATHWAY_GC_INTERVAL_S=0; a user who already
    disabled gc keeps their setting untouched.
    """

    def __init__(self) -> None:
        import gc
        import os

        self._gc = gc
        try:
            self._interval = float(os.environ.get("PATHWAY_GC_INTERVAL_S", "1.5"))
        except ValueError:
            self._interval = 1.5
        self._was_enabled = False
        self._stop: Any = None

    def __enter__(self) -> "_ManagedGc":
        if self._interval <= 0 or not self._gc.isenabled():
            return self
        import threading

        self._was_enabled = True
        self._gc.disable()
        self._stop = threading.Event()

        def caretaker(stop: Any, gc: Any, interval: float) -> None:
            sweeps = 0
            while not stop.wait(interval):
                sweeps += 1
                # young generations every sweep; a full collection every
                # 8th so gen-2 cycles (promoted survivors) cannot leak
                # for the lifetime of a long streaming run
                gc.collect(2 if sweeps % 8 == 0 else 1)

        t = threading.Thread(
            target=caretaker,
            args=(self._stop, self._gc, self._interval),
            name="pathway-gc",
            daemon=True,
        )
        t.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._was_enabled:
            self._stop.set()
            self._gc.enable()


def run_all(**kwargs: Any):
    return run(**kwargs)


def attach_prober(callback: Any) -> None:
    """Register a per-epoch stats callback (reference ``attach_prober`` /
    ``probe_table``, ``src/engine/graph.rs:988-995``): invoked by EVERY
    worker after each of its epochs with ``{"time", "worker",
    "operators", "connectors"}`` — per-worker partition stats like the
    reference's ProberStats; aggregate over ``worker`` for a fleet view."""
    G.engine_graph.probers.append(callback)
