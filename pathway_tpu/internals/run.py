"""``pw.run`` — execute the constructed dataflow.

Reference: ``python/pathway/internals/run.py`` + ``GraphRunner``
(``internals/graph_runner/__init__.py:36-252``).  Runs the epoch scheduler
over the global graph; with live connectors it blocks until all sources
close (streaming mode), mirroring ``pw.run`` blocking semantics.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"
    AUTO = "auto"


def run(
    *,
    monitoring_level: Any = MonitoringLevel.AUTO,
    with_http_server: bool = False,
    autocommit_duration_ms: int | None = 50,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    strict: bool | None = None,
    optimize: int | None = None,
    **kwargs: Any,
):
    """Run the whole computation graph (blocking until sources finish).

    ``strict=True`` (or ``PATHWAY_STRICT=1``) runs the pre-flight static
    analyzer (``pathway_tpu/analysis/``) and raises
    :class:`pathway_tpu.AnalysisError` on any error-severity finding —
    BEFORE the scheduler exists, so no connector thread ever starts.
    Finding counts are computed either way and surfaced through
    monitoring (``/status`` → ``analysis``).

    ``optimize`` sets the plan-compiler level (0 = off, 1 = const-fold +
    dead-column elimination + select/filter fusion, 2 = + append-only
    specialization + join pushdowns); default comes from
    ``PATHWAY_OPTIMIZE``, else 2.  The applied plan is available as
    ``pw.explain()`` / ``G.last_plan`` and on ``/status`` → ``plan``."""
    import os

    from pathway_tpu.internals import config as cfg

    if strict is None:
        strict = os.environ.get("PATHWAY_STRICT", "").lower() in (
            "1",
            "true",
            "yes",
        )
    analysis_counts: dict[str, int] = {}
    try:
        from pathway_tpu.analysis import (
            SEV_ERROR,
            AnalysisError,
            analyze,
            count_by_severity,
        )

        from pathway_tpu.analysis.rewrite import resolve_level as _rl

        # plan-aware: analyze the view the scheduler will execute, so
        # rewrites that cure a finding (dead-column elimination,
        # append-only reducer specialization) also clear its diagnostic
        diags = analyze(G.engine_graph, optimize=_rl(optimize))
        analysis_counts = count_by_severity(diags)
    except ImportError:
        diags = []
    if strict and any(d.severity == SEV_ERROR for d in diags):
        raise AnalysisError(diags)

    # plan compiler: rewrite a cloned execution view of the captured
    # graph; the captured graph itself stays pristine (re-runs, explain)
    exec_graph = G.engine_graph
    plan = None
    try:
        from pathway_tpu.analysis.rewrite import optimize_graph, resolve_level

        exec_graph, plan = optimize_graph(
            G.engine_graph, resolve_level(optimize)
        )
    except ImportError:
        pass
    G.last_plan = plan

    if persistence_config is None:
        persistence_config = cfg.pathway_config.persistence_config
    pc = cfg.pathway_config
    saved_typecheck = pc.runtime_typechecking
    if runtime_typechecking is not None:
        pc.runtime_typechecking = runtime_typechecking
    try:
        return _run_inner(
            pc,
            monitoring_level,
            with_http_server,
            autocommit_duration_ms,
            persistence_config,
            analysis_counts,
            exec_graph=exec_graph,
            plan=plan,
        )
    finally:
        # per-run override, not a process-wide setting
        pc.runtime_typechecking = saved_typecheck


def _run_inner(
    pc: Any,
    monitoring_level: Any,
    with_http_server: bool,
    autocommit_duration_ms: int | None,
    persistence_config: Any,
    analysis_counts: dict[str, int] | None = None,
    exec_graph: Any = None,
    plan: Any = None,
):
    import os

    from pathway_tpu.internals import config as cfg
    from pathway_tpu.internals.license import LicenseError, get_license

    threads = max(1, pc.threads)
    processes = max(1, pc.processes)
    # free tier caps total workers (reference MAX_WORKERS, config.rs:7-11).
    # Thread counts clamp locally; a process topology over the cap cannot
    # be shrunk from inside one process, so it is refused outright (every
    # process raises the same error).
    cap = get_license().worker_cap()
    if cap is not None and threads * processes > cap:
        if processes > cap:
            raise LicenseError(
                f"free tier allows at most {cap} workers but "
                f"PATHWAY_PROCESSES={processes}; set a license key with "
                "the 'scale' entitlement"
            )
        threads = max(1, cap // processes)
        import logging

        logging.getLogger("pathway_tpu.license").warning(
            "free tier caps workers at %d: running %d threads x %d "
            "processes = %d workers; set a license key with the 'scale' "
            "entitlement to lift the cap",
            cap,
            threads,
            processes,
            threads * processes,
        )
    sched = Scheduler(
        exec_graph if exec_graph is not None else G.engine_graph,
        autocommit_ms=autocommit_duration_ms or 50,
    )
    #: pre-flight analyzer finding counts, read by monitoring//status
    sched.analysis_findings = dict(analysis_counts or {})
    # a ClusterSupervisor stamps its respawn generation into the env so the
    # worker can surface it as pathway_tpu_worker_restarts_total
    try:
        sched.worker_restarts = int(os.environ.get("PATHWAY_WORKER_RESTARTS", "0"))
    except ValueError:
        sched.worker_restarts = 0
    #: optimizer audit trail + rewrite counters (monitoring//status)
    sched.execution_plan = plan
    sched.plan_counters = plan.counters() if plan is not None else {}
    #: static capacity estimate of the EXECUTING view, read by
    #: monitoring//status and /metrics next to the measured state bytes
    try:
        from pathway_tpu.analysis.memory import estimate_memory

        sched.memory_estimate = estimate_memory(
            exec_graph if exec_graph is not None else G.engine_graph,
            optimize=0,  # exec_graph is already the rewritten view
        )
    except Exception:
        sched.memory_estimate = None
    if with_http_server or cfg.pathway_config.monitoring_http_port:
        from pathway_tpu.internals.monitoring_server import start_http_server

        start_http_server(sched)
    # live TUI dashboard (reference pw.run(monitoring_level=...) rich TUI):
    # AUTO shows it only on a real terminal; NONE never
    show = monitoring_level in (MonitoringLevel.ALL, MonitoringLevel.IN_OUT)
    if monitoring_level == MonitoringLevel.AUTO:
        import sys

        show = sys.stderr.isatty()
    if show:
        try:
            from pathway_tpu.internals.monitoring import start_dashboard

            start_dashboard(
                sched,
                level=(
                    monitoring_level
                    if monitoring_level != MonitoringLevel.AUTO
                    else MonitoringLevel.ALL
                ),
            )
        except ImportError:
            pass  # rich unavailable: run silently
    if persistence_config is not None:
        from pathway_tpu.persistence import attach_persistence

        attach_persistence(sched, persistence_config)
    G.active_scheduler = sched  # handle for stopping threaded servers
    from pathway_tpu.internals.telemetry import get_telemetry

    telemetry = get_telemetry()
    with telemetry.span(
        "graph_runner.run", operators=len(G.engine_graph.nodes)
    ), _ManagedGc() as mgc:

        def _gc_tick() -> None:
            # the GC pacer is a wakeup source too: a sweep can take long
            # enough that parked workers' deadlines passed — notify the
            # scheduler's event waits so they re-evaluate immediately
            if mgc.maybe_sweep():
                sched.wake()

        sched.gc_tick = _gc_tick
        if threads * processes > 1:
            # multi-worker topology from the spawn env contract
            # (PATHWAY_THREADS × PATHWAY_PROCESSES, reference config.rs:86-120)
            from pathway_tpu.engine.cluster import Cluster

            cluster = Cluster(
                threads=threads,
                processes=processes,
                process_id=pc.process_id,
                first_port=pc.first_port,
            )
            try:
                ctx = sched.run_cluster(cluster)
            finally:
                cluster.close()
        else:
            ctx = sched.run()
    telemetry.record_process_metrics()
    telemetry.gauge("run.epoch", ctx.time)
    telemetry.gauge("run.errors", len(ctx.error_log))
    telemetry.export_metrics()
    G.last_run_ctx = ctx
    return ctx


class _ManagedGc:
    """Collector discipline for the run hot loop.

    CPython's automatic gen-0 collection fires every ~700 net container
    allocations; a streaming epoch allocates millions of short-lived row
    tuples, so the collector (plus the per-collection XLA gc callback JAX
    registers) costs ~2x wordcount throughput (measured: 183k -> 380k
    rows/s on the 400k-line benchmark).  The reference engine has no such
    pauses — Rust frees rows deterministically (src/engine/dataflow.rs) —
    so the TPU build's host runtime disables *automatic* collection for
    the duration of the run and sweeps at EPOCH BOUNDARIES instead (the
    scheduler calls :meth:`maybe_sweep` after each epoch).  Mid-epoch
    sweeps — the first design ran them from a timed caretaker thread —
    walk every transient row tuple alive inside the epoch and hold the
    GIL against the exchange reader threads, stalling peer processes; at
    the boundary the transients are already refcount-freed, so a sweep
    only walks live survivors (reducer state, buffers).  Startup objects
    (modules, the graph, jax internals — ~1M containers) are frozen out
    of the collector entirely for the run, and JAX's per-collection gc
    callback is detached while automatic collection is off.  Plain
    reference-counted garbage (the vast majority of row data) is freed
    immediately either way.  Opt out with PATHWAY_GC_INTERVAL_S=0; a
    user who already disabled gc keeps their setting untouched.
    """

    def __init__(self) -> None:
        import gc
        import os
        import time

        self._gc = gc
        self._time = time
        try:
            self._interval = float(os.environ.get("PATHWAY_GC_INTERVAL_S", "2.0"))
        except ValueError:
            self._interval = 2.0
        self._was_enabled = False
        self._last_sweep = 0.0
        self._next_due = 0.0
        self._sweeps = 0
        self._detached_callbacks: list[Any] = []

    def __enter__(self) -> "_ManagedGc":
        if self._interval <= 0 or not self._gc.isenabled():
            return self
        self._was_enabled = True
        self._gc.disable()
        # jax registers a gc callback that runs on every collection
        # (measured ~125ms each on this host); with automatic collection
        # off, our explicit sweeps don't need it either
        for cb in list(self._gc.callbacks):
            if "jax" in (getattr(cb, "__module__", "") or ""):
                self._gc.callbacks.remove(cb)
                self._detached_callbacks.append(cb)
        # clean the YOUNG generations, then freeze everything into the
        # permanent generation.  A full collect here walks gen-2 — with a
        # million-row static table that is ~1s before the run even starts
        # — for the sole benefit of not freezing old cyclic garbage; that
        # garbage is bounded (startup imports) and unfreezes at exit.
        self._gc.collect(1)
        self._gc.freeze()
        self._last_sweep = self._time.monotonic()
        self._next_due = self._last_sweep + self._interval
        return self

    def maybe_sweep(self) -> bool:
        """Sweep cycles if due — called by the scheduler between epochs,
        when transient row data is already dead.  Sweeps are PACED by
        their own cost: a sweep that took ``t`` seconds pushes the next
        one at least ``t / 0.02`` seconds out, bounding collector
        overhead to ~2% of runtime.  A fixed wall interval instead
        charges every process the full sweep cost per interval, which on
        a shared core compounds — slower runs sweep more, sweeping makes
        them slower (measured 0.25/0.8/1.6 CPU-seconds of gen-1 collects
        at 1/2/4 processes on the 2M-line wordcount).  Cycle garbage
        only accumulates from the few objects that survive epochs, so
        deferring sweeps costs memory slowly; leaks still get collected,
        just amortized.  Returns True when a sweep actually ran (the
        caller treats that as a wakeup-worthy event)."""
        if not self._was_enabled:
            return False
        now = self._time.monotonic()
        if now < self._next_due:
            return False
        self._sweeps += 1
        # young generations every sweep; a full collection every 8th so
        # gen-2 cycles (promoted survivors) cannot leak over a long
        # streaming run
        t0 = self._time.monotonic()
        self._gc.collect(2 if self._sweeps % 8 == 0 else 1)
        self._last_sweep = self._time.monotonic()
        cost = self._last_sweep - t0
        self._next_due = self._last_sweep + max(self._interval, cost / 0.02)
        return True

    def __exit__(self, *exc: Any) -> None:
        if self._was_enabled:
            self._gc.unfreeze()
            for cb in self._detached_callbacks:
                self._gc.callbacks.append(cb)
            self._detached_callbacks.clear()
            self._gc.enable()


def run_all(**kwargs: Any):
    return run(**kwargs)


def attach_prober(callback: Any) -> None:
    """Register a per-epoch stats callback (reference ``attach_prober`` /
    ``probe_table``, ``src/engine/graph.rs:988-995``): invoked by EVERY
    worker after each of its epochs with ``{"time", "worker",
    "operators", "connectors"}`` — per-worker partition stats like the
    reference's ProberStats; aggregate over ``worker`` for a fleet view."""
    G.engine_graph.probers.append(callback)
