"""``pw.load_yaml`` — deployable app templates (reference
``internals/yaml_loader.py:74-218``): ``$var`` references and
``!pw.some.Class`` instantiation tags."""

from __future__ import annotations

import importlib
from typing import Any, IO

import yaml

__all__ = ["load_yaml"]


class _Tagged:
    def __init__(self, path: str, value: Any):
        self.path = path
        self.value = value


def _construct_tagged(loader: yaml.Loader, tag_suffix: str, node: yaml.Node) -> Any:
    if isinstance(node, yaml.MappingNode):
        value = loader.construct_mapping(node, deep=True)
    elif isinstance(node, yaml.SequenceNode):
        value = loader.construct_sequence(node, deep=True)
    else:
        value = loader.construct_scalar(node)
    return _Tagged(tag_suffix, value)


class _Loader(yaml.SafeLoader):
    pass


_Loader.add_multi_constructor("!", _construct_tagged)


def _resolve_path(path: str) -> Any:
    """'pw.xpacks.llm.embedders.TPUEncoderEmbedder' -> the object."""
    parts = path.split(".")
    if parts[0] in ("pw", "pathway", "pathway_tpu"):
        module: Any = importlib.import_module("pathway_tpu")
        parts = parts[1:]
    else:
        module = importlib.import_module(parts[0])
        parts = parts[1:]
    obj = module
    for i, p in enumerate(parts):
        try:
            obj = getattr(obj, p)
        except AttributeError:
            # maybe a submodule not yet imported
            obj = importlib.import_module(
                obj.__name__ + "." + p if hasattr(obj, "__name__") else p
            )
    return obj


def _instantiate(node: Any, variables: dict[str, Any]) -> Any:
    if isinstance(node, _Tagged):
        target = _resolve_path(node.path)
        value = _instantiate(node.value, variables)
        if isinstance(value, dict):
            return target(**value) if callable(target) else target
        if value in (None, ""):
            return target() if callable(target) else target
        if isinstance(value, list):
            return target(*value)
        return target(value)
    if isinstance(node, dict):
        return {k: _instantiate(v, variables) for k, v in node.items()}
    if isinstance(node, list):
        return [_instantiate(v, variables) for v in node]
    if isinstance(node, str) and node.startswith("$"):
        name = node[1:]
        if name in variables:
            return variables[name]
        raise KeyError(f"undefined yaml variable ${name}")
    return node


def load_yaml(stream: str | bytes | IO) -> Any:
    """Parse a config with ``$var`` references and ``!pw.x.y.Class`` object
    tags (reference ``pw.load_yaml``)."""
    raw = yaml.load(stream, Loader=_Loader)  # noqa: S506 — custom safe loader
    if not isinstance(raw, dict):
        return _instantiate(raw, {})
    # top-level keys are $variables for each other, regardless of document
    # order: resolve iteratively, deferring keys whose $refs aren't ready
    # yet.  A leading $ on a KEY marks a private variable (reference app
    # templates: "$llm:", "$sources:", ... referenced as $llm) — the $ is
    # not part of the variable name, and $-keys are dropped from the
    # returned config.
    variables: dict[str, Any] = {}
    todo: dict[Any, Any] = {}
    private: set = set()
    for k, v in raw.items():
        if isinstance(k, str) and k.startswith("$$"):
            name = k[1:]  # escaped: "$$x" is the literal key "$x"
        elif isinstance(k, str) and k.startswith("$"):
            name = k[1:]
            private.add(name)
        else:
            name = k
        if name in todo:
            raise KeyError(
                f"yaml config defines both {k!r} and a key that resolves "
                f"to the same variable name {name!r}"
            )
        todo[name] = v
    while todo:
        progressed = False
        deferred: dict[str, Any] = {}
        last_error: Exception | None = None
        for key, value in todo.items():
            try:
                variables[key] = _instantiate(value, variables)
                progressed = True
            except KeyError as e:
                deferred[key] = value
                last_error = e
        if not progressed:
            raise KeyError(
                f"unresolvable yaml variable reference(s): {last_error}"
            )
        todo = deferred
    return {k: v for k, v in variables.items() if k not in private}
