"""JoinResult: ``t1.join(t2, t1.a == t2.b).select(...)``.

Capability parity with reference ``python/pathway/internals/joins.py`` (1422
LoC): inner/left/right/outer equi-joins with ``pw.left``/``pw.right``/
``pw.this`` resolution in the projection, chained filter, and id assignment.
"""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    BinaryExpression,
    ColumnExpression,
    ColumnReference,
    _wrap,
    smart_name,
)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table, _Layout
from pathway_tpu.internals.thisclass import ThisMetaclass
from pathway_tpu.internals.thisclass import left as LEFT
from pathway_tpu.internals.thisclass import right as RIGHT
from pathway_tpu.internals.thisclass import this as THIS


class JoinKind(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


JoinMode = JoinKind  # reference alias pw.JoinMode


def _side_of(expr: ColumnExpression, left: Table, right: Table) -> str:
    sides = set()
    for r in expr._references():
        t = r._table
        if t is LEFT:
            sides.add("left")
        elif t is RIGHT:
            sides.add("right")
        # table IDENTITY decides before layout tokens: a self-join via
        # t.copy() shares t's layout token on both sides, and the token
        # fallback alone would call both references "left"
        elif t is left:
            sides.add("left")
        elif t is right:
            sides.add("right")
        elif getattr(t, "_layout_token", object()) is left._layout_token:
            sides.add("left")
        elif getattr(t, "_layout_token", object()) is right._layout_token:
            sides.add("right")
        else:
            raise ValueError(f"join condition references unknown table: {r!r}")
    if len(sides) != 1:
        raise ValueError(f"join condition side is ambiguous: {expr!r}")
    return sides.pop()


class JoinResult:
    def __init__(
        self,
        left: Table,
        right: Table,
        on: list[Any],
        kind: JoinKind,
        assign_id: Any = None,
        _node: eg.Node | None = None,
    ):
        self._left = left
        self._right = right
        self._kind = kind
        self._assign_id = assign_id
        if _node is not None:
            self._node = _node
            return

        left_exprs: list[ColumnExpression] = []
        right_exprs: list[ColumnExpression] = []
        for cond in on:
            cond = _wrap(cond)._substitute({LEFT: left, RIGHT: right})
            if not (isinstance(cond, BinaryExpression) and cond._op == "=="):
                raise ValueError("join conditions must be equalities: t1.a == t2.b")
            a, b = cond._left, cond._right
            if _side_of(a, left, right) == "left":
                left_exprs.append(a)
                right_exprs.append(b)
                if _side_of(b, left, right) != "right":
                    raise ValueError("join condition must compare left vs right")
            else:
                left_exprs.append(b)
                right_exprs.append(a)
                if _side_of(b, left, right) != "left":
                    raise ValueError("join condition must compare left vs right")

        llayout = left._layout()
        rlayout = right._layout()
        lfns = [e._compile(llayout.resolver) for e in left_exprs]
        rfns = [e._compile(rlayout.resolver) for e in right_exprs]

        def left_jk(key: Any, values: tuple) -> tuple:
            kv = (key, values)
            return tuple(f(kv) for f in lfns)

        def right_jk(key: Any, values: tuple) -> tuple:
            kv = (key, values)
            return tuple(f(kv) for f in rfns)

        left_id_only = False
        if assign_id is not None:
            ref = assign_id
            if isinstance(ref, ColumnReference) and ref._name == "id":
                if ref._table is left or ref._table is LEFT:
                    left_id_only = True

        # native epoch pass: one VM program per side computing the whole
        # join-key tuple (internals/expr_vm.py); falls back to the
        # closures above when lowering is unavailable
        from pathway_tpu.internals import expr_vm as _vm
        from pathway_tpu.internals.expression import MakeTupleExpression

        lprog = _vm.lower_program(MakeTupleExpression(*left_exprs), llayout)
        rprog = _vm.lower_program(MakeTupleExpression(*right_exprs), rlayout)
        jk_programs = (
            (lprog, rprog) if lprog is not None and rprog is not None else None
        )

        self._node = eg.JoinNode(
            G.engine_graph,
            left._node,
            right._node,
            left_jk,
            right_jk,
            left_ncols=len(left._column_names),
            right_ncols=len(right._column_names),
            kind=kind.value,
            left_id_only=left_id_only,
            jk_programs=jk_programs,
        )
        self._node.meta["join"] = {
            "kind": kind.value,
            "on": [
                (
                    smart_name(le) or "<expr>",
                    getattr(le, "_dtype", dt.ANY),
                    smart_name(re_) or "<expr>",
                    getattr(re_, "_dtype", dt.ANY),
                )
                for le, re_ in zip(left_exprs, right_exprs)
            ],
        }

    # ------------------------------------------------------------------
    def _layout(self) -> _Layout:
        left, right = self._left, self._right
        ln = len(left._column_names)
        rn = len(right._column_names)
        layout = _Layout()
        lmap = {c: i for i, c in enumerate(left._column_names)}
        rmap = {c: ln + i for i, c in enumerate(right._column_names)}
        layout.add(left, lmap, id_pos=ln + rn)
        layout.add(right, rmap, id_pos=ln + rn + 1)
        union: dict[str, int | None] = {}
        for c, i in lmap.items():
            union[c] = i
        for c, i in rmap.items():
            if c in union:
                union[c] = None  # None marks ambiguity; resolver raises
            else:
                union[c] = i
        layout.add(self, union, id_pos=None)
        return layout

    def _dtype_of(self, name: str, side: str) -> dt.DType:
        t = self._left if side == "left" else self._right
        base = t._dtypes.get(name, dt.ANY)
        if self._kind in (JoinKind.OUTER,) or (
            side == "left" and self._kind == JoinKind.RIGHT
        ) or (side == "right" and self._kind == JoinKind.LEFT):
            return dt.Optional(base)
        return base

    def select(self, *args: Any, **kwargs: Any) -> Table:
        left, right = self._left, self._right
        named: list[tuple[str, ColumnExpression]] = []

        def expand(placeholder: Any) -> None:
            if placeholder is LEFT:
                for c in left._column_names:
                    named.append((c, ColumnReference(left, c)))
            elif placeholder is RIGHT:
                for c in right._column_names:
                    named.append((c, ColumnReference(right, c)))
            elif placeholder is THIS:
                seen = set()
                for c in left._column_names:
                    named.append((c, ColumnReference(left, c)))
                    seen.add(c)
                for c in right._column_names:
                    if c not in seen:
                        named.append((c, ColumnReference(right, c)))

        for a in args:
            if isinstance(a, ThisMetaclass):
                expand(a)
                continue
            e = _wrap(a)._substitute({THIS: self, LEFT: left, RIGHT: right})
            n = smart_name(e)
            if n is None:
                raise ValueError("positional join select args must be column refs")
            named.append((n, e))
        for n, a in kwargs.items():
            named.append((n, _wrap(a)._substitute({THIS: self, LEFT: left, RIGHT: right})))

        # dedup, later wins
        dedup: dict[str, ColumnExpression] = {}
        for n, e in named:
            dedup[n] = e
        names = list(dedup.keys())
        exprs = list(dedup.values())

        layout = self._layout()
        compiled = [e._compile(layout.resolver) for e in exprs]

        def row_fn(key: Any, values: tuple) -> tuple:
            kv = (key, values)
            return tuple(c(kv) for c in compiled)

        node = eg.RowwiseNode(G.engine_graph, self._node, row_fn, name="join_select")
        node.meta["used_cols"] = sorted(
            {
                r._name
                for e in exprs
                for r in e._references()
                if r._name != "id"
            }
        )
        dtypes: dict[str, dt.DType] = {}
        for n, e in zip(names, exprs):
            if isinstance(e, ColumnReference) and not isinstance(e._table, ThisMetaclass):
                if e._table is left or getattr(e._table, "_layout_token", None) is left._layout_token:
                    dtypes[n] = self._dtype_of(e._name, "left") if e._name != "id" else dt.POINTER
                elif e._table is right or getattr(e._table, "_layout_token", None) is right._layout_token:
                    dtypes[n] = self._dtype_of(e._name, "right") if e._name != "id" else dt.POINTER
                else:
                    dtypes[n] = e._dtype
            else:
                dtypes[n] = e._dtype
        node.meta["select"] = {
            "kind": "join_select",
            "names": names,
            "exprs": exprs,
            "layout": layout,
            "dtypes": [dtypes[n] for n in names],
        }
        return Table(node, names, dtypes, name="join")

    def filter(self, expr: Any) -> "JoinResult":
        e = _wrap(expr)._substitute({THIS: self, LEFT: self._left, RIGHT: self._right})
        layout = self._layout()
        c = e._compile(layout.resolver)
        fnode = eg.FilterNode(
            G.engine_graph, self._node, lambda key, values: c((key, values))
        )
        fnode.meta["filter"] = {"exprs": [e], "layout": layout}
        # frame marker: the predicate is over the raw join output frame
        # (lv + rv + (lk, rk)), which is what lets the optimizer push it
        # below the join without substitution
        fnode.meta["join_filter"] = {
            "left_ncols": len(self._left._column_names),
            "right_ncols": len(self._right._column_names),
        }
        fnode.meta["used_cols"] = sorted(
            {r._name for r in e._references() if r._name != "id"}
        )
        return JoinResult(
            self._left, self._right, [], self._kind, self._assign_id, _node=fnode
        )

    # column references on the join result (pw.this style)
    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    @property
    def _column_names(self) -> list[str]:
        seen = list(self._left._column_names)
        for c in self._right._column_names:
            if c not in seen:
                seen.append(c)
        return seen

    @property
    def _dtypes(self) -> dict[str, dt.DType]:
        out = {c: self._dtype_of(c, "left") for c in self._left._column_names}
        for c in self._right._column_names:
            out.setdefault(c, self._dtype_of(c, "right"))
        return out

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        return self.select(THIS).reduce(*args, **kwargs)

    def groupby(self, *args: Any, **kwargs: Any) -> Any:
        return self.select(THIS).groupby(*args, **kwargs)
