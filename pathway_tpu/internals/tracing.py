"""Low-overhead distributed tracing with an always-on flight recorder.

Every span is one tuple appended into a **per-thread fixed-size ring
buffer** — the record path is a tuple build plus a list-slot assignment
and an index increment, with **no locks, no allocation beyond the tuple,
no syscalls** (``scripts/check_locks.py`` lints this file; the LK007
whole-repo lock graph must stay cycle-free and the only lock here is the
leaf-level ring registry mutex, taken once per thread at ring creation
and on the dump path — never per record).

Record layout (one tuple per span)::

    (trace_id, span_id, parent_id, stage, rank, t0_ns, t1_ns, sampled, args)

``t0_ns``/``t1_ns`` are ``time.monotonic_ns()`` — on Linux
CLOCK_MONOTONIC is machine-wide, so spans recorded by *different
processes on one host* share a timebase and stitch into one causal
timeline without clock translation (the 2-proc chaos drills rely on
this).

Sampling: the ring is **always on** (that is what makes it a flight
recorder — the last ``ring_size`` spans per thread are always there for
a post-mortem dump), so head sampling governs *export*, not recording:

- ``PATHWAY_TRACE_SAMPLE`` (0..1, default 1.0) — fraction of new traces
  marked ``sampled``; only sampled traces appear in on-demand exports
  (``/debug/trace``, ``chrome_events()``) unless ``all_spans=True``.
- ``PATHWAY_TRACE_TAIL_MS`` (default 250) — a request whose end-to-end
  latency exceeds this is **always kept**: :func:`finish_request` adds
  its trace id to a bounded tail-keep ring, resurrecting the trace in
  exports even when head sampling skipped it.  Slow requests are the
  ones worth attributing; the knob guarantees they survive sampling.

Other knobs: ``PATHWAY_TRACE=0`` disables recording entirely (the
bench overhead gate A/Bs this), ``PATHWAY_TRACE_RING`` sizes the
per-thread ring (default 4096 spans), and ``PATHWAY_TRACE_DIR`` names
the flight-recorder spool: when set, :func:`flush` writes
``trace-r{rank}-*.json`` Chrome-trace files there (and an atexit hook
flushes on clean process exit).  Dump triggers wired elsewhere:
liveness trips (``engine/cluster.py`` ``_fail``/``_fail_peer``), chaos
kills (``testing/chaos.py`` flushes before ``os._exit``), supervisor
restarts (``internals/resilience.py`` merges the per-rank spool into
``merged_trace.json``), SIGUSR2 (:func:`install_sigusr2` — also dumps
all Python thread stacks), and ``/debug/trace?seconds=N`` on the
monitoring server.

Context propagation is ambient: :func:`use` pins a
:class:`TraceContext` to the current thread, :func:`span` opens a child
span under it (re-parenting nested spans), and the serving/cluster
layers carry contexts across thread and process hops explicitly —
serving requests on the request object, cluster epochs piggybacked on
the round-status exchange frames (``Cluster.round_statuses``).
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
import traceback
from typing import Any, Callable, Iterator

__all__ = [
    "TraceContext",
    "chrome_events",
    "configure",
    "current",
    "current_rank",
    "dump",
    "dump_stacks",
    "enabled",
    "finish_request",
    "flush",
    "install_sigusr2",
    "merge_trace_dir",
    "new_trace",
    "now_ns",
    "record_span",
    "record_spans",
    "reset",
    "set_ambient",
    "set_rank",
    "span",
    "use",
]

_monotonic_ns = time.monotonic_ns

#: the span clock (machine-wide monotonic, so spans from different
#: processes on one host line up without translation)
now_ns = time.monotonic_ns

#: tail-keep ring capacity (trace ids of slow requests kept past sampling)
_KEPT_CAP = 4096


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Config:
    __slots__ = ("on", "sample", "tail_ns", "ring_size", "spool_dir")

    def __init__(self) -> None:
        self.reload()

    def reload(self) -> None:
        self.on = os.environ.get("PATHWAY_TRACE", "1") != "0"
        self.sample = min(1.0, max(0.0, _env_float("PATHWAY_TRACE_SAMPLE", 1.0)))
        self.tail_ns = int(_env_float("PATHWAY_TRACE_TAIL_MS", 250.0) * 1e6)
        self.ring_size = max(64, _env_int("PATHWAY_TRACE_RING", 4096))
        self.spool_dir = os.environ.get("PATHWAY_TRACE_DIR") or None


_cfg = _Config()

#: process rank stamped into every span (supervised workers inherit it
#: from the spawn env; in-process tests may override via set_rank)
_rank = _env_int("PATHWAY_PROCESS_ID", 0)

#: leaf lock: ring registration + dump/flush serialization only — NEVER
#: on the record path, and nothing is acquired while it is held
_registry_mutex = threading.Lock()
_rings: list["_Ring"] = []

#: bounded tail-keep ring: trace ids of requests over the tail threshold
#: (preallocated; racy slot assignment loses at most one id — benign)
_kept: list[int] = [0] * _KEPT_CAP
_kept_idx = 0

_atexit_installed = False


class _Ring:
    """One thread's span ring: preallocated slots, lock-free append."""

    __slots__ = ("buf", "idx", "cap", "thread_name", "id_next")

    def __init__(self, cap: int, thread_name: str, id_seed: int):
        self.cap = cap
        self.buf: list[Any] = [None] * cap
        self.idx = 0
        self.thread_name = thread_name
        self.id_next = id_seed

    def snapshot(self) -> list[tuple]:
        """Copy the live records in append order (dump path; the copy is
        a single C-level list() under the GIL, racing appends at worst
        tear the oldest slot, which is dropped by the None filter)."""
        buf = list(self.buf)
        i = self.idx
        if i <= self.cap:
            out = buf[:i]
        else:
            head = i % self.cap
            out = buf[head:] + buf[:head]
        return [r for r in out if r is not None]


class _Tls(threading.local):
    ring: "_Ring | None" = None
    ctx: "TraceContext | None" = None


_tls = _Tls()


def _make_ring() -> _Ring:
    t = threading.current_thread()
    # seeded per ring so span ids are unique across threads/processes
    # without coordination: high bits random, low bits a local counter
    seed = (random.getrandbits(30) << 33) | (os.getpid() & 0xFFFF) << 17
    ring = _Ring(_cfg.ring_size, t.name, seed)
    with _registry_mutex:
        _rings.append(ring)
    _tls.ring = ring
    global _atexit_installed
    if _cfg.spool_dir and not _atexit_installed:
        _atexit_installed = True
        import atexit

        atexit.register(lambda: flush("exit"))
    return ring


class TraceContext:
    """One request's (or epoch's) propagated identity: which trace the
    next span belongs to and which span is its parent."""

    __slots__ = ("trace_id", "span_id", "sampled", "t0_ns")

    def __init__(self, trace_id: int, span_id: int = 0, sampled: bool = True,
                 t0_ns: int = 0):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.t0_ns = t0_ns

    def child(self, span_id: int) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.sampled, self.t0_ns)

    def to_wire(self) -> tuple[int, int, bool]:
        """Compact form piggybacked on cluster exchange frames."""
        return (self.trace_id, self.span_id, self.sampled)

    @staticmethod
    def from_wire(wire: Any) -> "TraceContext | None":
        try:
            trace_id, span_id, sampled = wire
            return TraceContext(int(trace_id), int(span_id), bool(sampled))
        except (TypeError, ValueError):
            return None


# ----------------------------------------------------------------- config


def configure(**env: Any) -> None:
    """Apply env-style knobs programmatically and reload the config
    (tests and bench use this instead of mutating os.environ ad hoc)."""
    for key, value in env.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)
    _cfg.reload()


def enabled() -> bool:
    return _cfg.on


def set_rank(rank: int) -> None:
    global _rank
    _rank = int(rank)


def current_rank() -> int:
    return _rank


def reset() -> None:
    """Drop every registered ring and tail-keep entry (test isolation)."""
    global _kept_idx
    with _registry_mutex:
        _rings.clear()
    _tls.ring = None
    _tls.ctx = None
    for i in range(_KEPT_CAP):
        _kept[i] = 0
    _kept_idx = 0
    _cfg.reload()


# ------------------------------------------------------------ record path


def _next_id() -> int:
    ring = _tls.ring
    if ring is None:
        ring = _make_ring()
    ring.id_next += 1
    return ring.id_next


def new_trace(sampled: bool | None = None) -> TraceContext:
    """Open a new trace (one per serving request / epoch).  Draws the
    head-sampling decision unless ``sampled`` is forced."""
    trace_id = _next_id()
    if sampled is None:
        s = _cfg.sample
        sampled = s >= 1.0 or (s > 0.0 and random.random() < s)
    return TraceContext(trace_id, trace_id, sampled, _monotonic_ns())


def current() -> TraceContext | None:
    """The thread's ambient trace context (None outside any request)."""
    return _tls.ctx


class _Use:
    __slots__ = ("ctx", "prev")

    def __init__(self, ctx: TraceContext | None):
        self.ctx = ctx
        self.prev: TraceContext | None = None

    def __enter__(self) -> TraceContext | None:
        self.prev = _tls.ctx
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc: Any) -> None:
        _tls.ctx = self.prev


def use(ctx: TraceContext | None) -> _Use:
    """Pin ``ctx`` as the thread's ambient context for a ``with`` block
    (stage workers adopt the request's context this way)."""
    return _Use(ctx)


def set_ambient(ctx: TraceContext | None) -> TraceContext | None:
    """Swap the thread's ambient context, returning the previous one.
    The try/finally flavor of :func:`use` for per-task hot loops where
    the CM's object + enter/exit dispatch is measurable."""
    tls = _tls
    prev = tls.ctx
    tls.ctx = ctx
    return prev


def record_span(
    stage: str,
    t0_ns: int,
    t1_ns: int,
    ctx: TraceContext | None = None,
    args: dict | None = None,
) -> int:
    """Record one completed span; returns its span id (0 when tracing is
    off).  THE hot path: no locks, no I/O — one tuple into the ring."""
    if not _cfg.on:
        return 0
    tls = _tls
    ring = tls.ring
    if ring is None:
        ring = _make_ring()
    span_id = ring.id_next = ring.id_next + 1
    if ctx is None:
        ctx = tls.ctx
    if ctx is not None:
        rec = (ctx.trace_id, span_id, ctx.span_id, stage, _rank,
               t0_ns, t1_ns, ctx.sampled, args)
    else:
        rec = (0, span_id, 0, stage, _rank, t0_ns, t1_ns, False, args)
    ring.buf[ring.idx % ring.cap] = rec
    ring.idx += 1
    return span_id


def record_spans(
    ctx: TraceContext | None,
    spans: "list[tuple[str, int, int, dict | None]]",
) -> None:
    """Record a batch of completed ``(stage, t0_ns, t1_ns, args)`` spans
    under ``ctx`` in one call.  The serving path stamps raw timestamps as
    a request moves through its stages (it needs them for the latency
    probes anyway) and materializes all spans here at request end —
    one call per request instead of one per stage."""
    if not _cfg.on or ctx is None:
        return
    ring = _tls.ring
    if ring is None:
        ring = _make_ring()
    buf, cap = ring.buf, ring.cap
    i, nid = ring.idx, ring.id_next
    trace_id, parent, sampled = ctx.trace_id, ctx.span_id, ctx.sampled
    rank = _rank
    for stage, t0_ns, t1_ns, args in spans:
        nid += 1
        buf[i % cap] = (trace_id, nid, parent, stage, rank,
                        t0_ns, t1_ns, sampled, args)
        i += 1
    ring.id_next = nid
    ring.idx = i


class _Span:
    """Hot-path span CM.  Doubles as the child TraceContext while the
    block runs (it carries trace_id/span_id/sampled/t0_ns, which is all
    record_span reads), so entering a span allocates no extra object."""

    __slots__ = ("stage", "args", "parent", "t0_ns", "prev",
                 "trace_id", "span_id", "sampled")

    def __init__(self, stage: str, args: dict | None, ctx: TraceContext | None):
        self.stage = stage
        self.args = args
        self.parent = ctx

    def __enter__(self) -> "_Span":
        tls = _tls
        self.prev = tls.ctx
        if not _cfg.on:
            # tracing off: no id, no ambient swap, no clock read; the
            # zero t0 tells __exit__ to skip even if toggled on mid-block
            self.parent = None
            self.t0_ns = 0
            return self
        ctx = self.parent if self.parent is not None else self.prev
        self.parent = ctx
        if ctx is not None:
            # pre-allocate this span's id so children recorded inside the
            # block parent onto it (the record at exit reuses the id)
            ring = tls.ring
            if ring is None:
                ring = _make_ring()
            ring.id_next += 1
            self.trace_id = ctx.trace_id
            self.span_id = ring.id_next
            self.sampled = ctx.sampled
            tls.ctx = self
        self.t0_ns = _monotonic_ns()
        return self

    def __exit__(self, et: Any, ev: Any, tb: Any) -> None:
        tls = _tls
        tls.ctx = self.prev
        if not _cfg.on or self.t0_ns == 0:
            return
        t1 = _monotonic_ns()
        ring = tls.ring
        if ring is None:
            ring = _make_ring()
        parent = self.parent
        if parent is not None:
            rec = (self.trace_id, self.span_id, parent.span_id,
                   self.stage, _rank, self.t0_ns, t1, self.sampled,
                   self.args)
        else:
            ring.id_next += 1
            rec = (0, ring.id_next, 0, self.stage, _rank, self.t0_ns, t1,
                   False, self.args)
        ring.buf[ring.idx % ring.cap] = rec
        ring.idx += 1


def span(stage: str, args: dict | None = None,
         ctx: TraceContext | None = None) -> _Span:
    """Time a ``with`` block as one span under the ambient (or given)
    context; nested ``span()`` calls inside the block parent onto it."""
    return _Span(stage, args, ctx)


def finish_request(ctx: TraceContext | None, t1_ns: int | None = None) -> None:
    """Mark a request finished: if its end-to-end latency crossed the
    tail threshold, keep its trace regardless of head sampling."""
    global _kept_idx
    if ctx is None or not _cfg.on:
        return
    t1 = t1_ns if t1_ns is not None else _monotonic_ns()
    if ctx.t0_ns and (t1 - ctx.t0_ns) >= _cfg.tail_ns:
        i = _kept_idx
        _kept[i % _KEPT_CAP] = ctx.trace_id
        _kept_idx = i + 1


# ------------------------------------------------------------- dump path


def snapshot_records() -> list[tuple]:
    """Every live ring's records, append order per ring."""
    with _registry_mutex:
        rings = list(_rings)
    out: list[tuple] = []
    for ring in rings:
        out.extend(ring.snapshot())
    return out


def _ring_names() -> dict[int, str]:
    with _registry_mutex:
        return {id(r): r.thread_name for r in _rings}


def chrome_events(
    since_ns: int | None = None, all_spans: bool = False
) -> list[dict]:
    """Render the rings as Chrome-trace / Perfetto ``traceEvents``
    (``ph: "X"`` complete events; ``pid`` = rank, ``tid`` = thread).

    Export filter: spans of sampled traces, spans of tail-kept traces,
    and context-free spans (``trace_id == 0`` — flight-recorder noise
    floor) — or everything with ``all_spans=True``."""
    kept = set(_kept) - {0}
    events: list[dict] = []
    with _registry_mutex:
        rings = list(_rings)
    for ring in rings:
        tid = ring.thread_name
        for rec in ring.snapshot():
            trace_id, span_id, parent, stage, rank, t0, t1, sampled, args = rec
            if since_ns is not None and t1 < since_ns:
                continue
            if not all_spans and trace_id and not sampled and trace_id not in kept:
                continue
            ev_args = {"trace_id": trace_id, "span_id": span_id,
                       "parent": parent}
            if args:
                ev_args.update(args)
            events.append({
                "ph": "X",
                "name": stage,
                "cat": "pathway",
                "pid": rank,
                "tid": tid,
                "ts": t0 / 1e3,
                "dur": max(t1 - t0, 0) / 1e3,
                "args": ev_args,
            })
    events.sort(key=lambda e: e["ts"])
    return events


def dump(path: str, *, since_ns: int | None = None,
         all_spans: bool = True) -> str:
    """Write a Chrome-trace JSON file (open it at ui.perfetto.dev or
    chrome://tracing).  Flight-recorder dumps default to ``all_spans``:
    a post-mortem wants everything the ring still holds."""
    doc = {
        "traceEvents": chrome_events(since_ns=since_ns, all_spans=all_spans),
        "displayTimeUnit": "ms",
        "otherData": {"rank": _rank, "pid": os.getpid()},
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


_flush_n = 0


def flush(reason: str = "manual") -> str | None:
    """Flight-recorder flush: dump this process's rings into the spool
    dir (``PATHWAY_TRACE_DIR``).  No-op (None) when no spool is set.
    Safe to call from failure paths — never raises."""
    global _flush_n
    spool = _cfg.spool_dir
    if not spool:
        return None
    try:
        os.makedirs(spool, exist_ok=True)
        with _registry_mutex:
            _flush_n += 1
            n = _flush_n
        path = os.path.join(
            spool, f"trace-r{_rank}-p{os.getpid()}-{n:03d}-{reason}.json"
        )
        return dump(path)
    except Exception:  # noqa: BLE001 — a failing dump must not mask the failure
        return None


def merge_trace_dir(spool: str, out_path: str | None = None) -> str | None:
    """Merge every per-rank ``trace-*.json`` in ``spool`` into ONE
    Chrome-trace file (default ``<spool>/merged_trace.json``) — the
    single stitched timeline the chaos drills assert on.  Events keep
    their per-rank ``pid``; duplicate (span_id, rank) pairs from repeat
    flushes of one ring collapse to the last occurrence."""
    try:
        names = sorted(
            f for f in os.listdir(spool)
            if f.startswith("trace-") and f.endswith(".json")
        )
    except OSError:
        return None
    if not names:
        return None
    by_key: dict[Any, dict] = {}
    loose: list[dict] = []
    for name in names:
        try:
            with open(os.path.join(spool, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        for ev in doc.get("traceEvents", ()):
            sid = ev.get("args", {}).get("span_id")
            if sid:
                by_key[(ev.get("pid"), sid)] = ev
            else:
                loose.append(ev)
    events = list(by_key.values()) + loose
    events.sort(key=lambda e: e.get("ts", 0))
    out_path = out_path or os.path.join(spool, "merged_trace.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out_path)
    return out_path


# --------------------------------------------------- stacks + SIGUSR2


def dump_stacks() -> str:
    """Every Python thread's stack as text (hang diagnosis; served by
    ``/debug/stacks`` and written to stderr on SIGUSR2)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    parts: list[str] = []
    for ident, frame in frames.items():
        name = names.get(ident, "?")
        parts.append(f"--- Thread {name} (ident {ident}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(parts) + "\n"


_sigusr2_installed = False


def install_sigusr2() -> bool:
    """SIGUSR2 → dump all thread stacks to stderr AND flush the flight
    recorder to the spool dir.  Main-thread only (signal module rule);
    returns False when it cannot install."""
    global _sigusr2_installed
    if _sigusr2_installed:
        return True
    try:
        import signal

        def _handler(_signum: int, _frame: Any) -> None:
            try:
                sys.stderr.write(dump_stacks())
                sys.stderr.flush()
            except Exception:  # noqa: BLE001
                pass
            flush("sigusr2")

        signal.signal(signal.SIGUSR2, _handler)
        _sigusr2_installed = True
        return True
    except (ValueError, OSError, AttributeError):
        return False  # not the main thread, or no SIGUSR2 (non-POSIX)
