"""Dynamic type lattice for table columns.

Capability parity with the reference type system (reference:
``python/pathway/internals/dtype.py``, ``src/engine/value.rs:207-231``) but
designed fresh: a small closed set of scalar dtypes plus parametric
Optional/Tuple/List/Array/Pointer/Callable wrappers, with a ``lub`` (least
upper bound) used by concat/if_else/coalesce type inference.
"""

from __future__ import annotations

import datetime
import typing
from dataclasses import dataclass
from typing import Any as _Any

import numpy as np


class DType:
    """Base of all column dtypes."""

    name: str = "DType"

    def __repr__(self) -> str:
        return self.name

    def is_optional(self) -> bool:
        return False

    def strip_optional(self) -> "DType":
        return self

    def is_value_compatible(self, value: _Any) -> bool:  # pragma: no cover
        raise NotImplementedError

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and repr(self) == repr(other)

    def __hash__(self) -> int:
        return hash((type(self).__name__, repr(self)))


class _SimpleDType(DType):
    def __init__(self, name: str, py_types: tuple[type, ...]):
        self.name = name
        self.py_types = py_types

    def is_value_compatible(self, value: _Any) -> bool:
        if self.name == "FLOAT" and isinstance(value, (int, float)):
            return not isinstance(value, bool)
        if self.name == "INT" and isinstance(value, bool):
            return False
        if self.name == "BOOL":
            return isinstance(value, (bool, np.bool_))
        return isinstance(value, self.py_types)


ANY = _SimpleDType("ANY", (object,))
NONE = _SimpleDType("NONE", (type(None),))
BOOL = _SimpleDType("BOOL", (bool,))
INT = _SimpleDType("INT", (int,))
FLOAT = _SimpleDType("FLOAT", (float,))
STR = _SimpleDType("STR", (str,))
BYTES = _SimpleDType("BYTES", (bytes,))
DATE_TIME_NAIVE = _SimpleDType("DATE_TIME_NAIVE", (datetime.datetime,))
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC", (datetime.datetime,))
DURATION = _SimpleDType("DURATION", (datetime.timedelta,))
JSON = _SimpleDType("JSON", (object,))
PY_OBJECT_WRAPPER = _SimpleDType("PY_OBJECT_WRAPPER", (object,))


class Optional(DType):
    def __init__(self, wrapped: DType):
        if isinstance(wrapped, Optional):
            wrapped = wrapped.wrapped
        self.wrapped = wrapped
        self.name = f"Optional({wrapped!r})"

    def is_optional(self) -> bool:
        return True

    def strip_optional(self) -> DType:
        return self.wrapped

    def is_value_compatible(self, value: _Any) -> bool:
        return value is None or self.wrapped.is_value_compatible(value)


class Pointer(DType):
    """Row reference (128-bit key); reference ``Value::Pointer``."""

    def __init__(self, *args: _Any):
        self.name = "POINTER"

    def is_value_compatible(self, value: _Any) -> bool:
        from pathway_tpu.internals.keys import Pointer as Ptr

        return isinstance(value, Ptr)


POINTER = Pointer()


class Tuple(DType):
    def __init__(self, *element_types: DType):
        self.element_types = element_types
        self.name = f"Tuple{element_types!r}"

    def is_value_compatible(self, value: _Any) -> bool:
        return isinstance(value, tuple)


class List(DType):
    def __init__(self, element_type: DType = ANY):
        self.element_type = element_type
        self.name = f"List({element_type!r})"

    def is_value_compatible(self, value: _Any) -> bool:
        return isinstance(value, (tuple, list))


class Array(DType):
    """N-dim numeric array (reference ``Value::FloatArray``/``IntArray``)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = FLOAT):
        self.n_dim = n_dim
        self.wrapped = wrapped
        self.name = f"Array({n_dim}, {wrapped!r})"

    def is_value_compatible(self, value: _Any) -> bool:
        return isinstance(value, np.ndarray) or hasattr(value, "__array__")


ANY_ARRAY = Array()


class Callable(DType):
    def __init__(self, *args: _Any):
        self.name = "CALLABLE"

    def is_value_compatible(self, value: _Any) -> bool:
        return callable(value)


class Future(DType):
    """Column whose values may still be pending (async UDF results)."""

    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        self.name = f"Future({wrapped!r})"

    def is_value_compatible(self, value: _Any) -> bool:
        from pathway_tpu.internals import api

        return value is api.PENDING or self.wrapped.is_value_compatible(value)


class DateTimeNaive(datetime.datetime):
    """Schema annotation for timezone-naive datetimes (reference
    ``pw.DateTimeNaive``)."""


class DateTimeUtc(datetime.datetime):
    """Schema annotation for timezone-aware datetimes (reference
    ``pw.DateTimeUtc``)."""


class Duration(datetime.timedelta):
    """Schema annotation for durations (reference ``pw.Duration``)."""


_FROM_PY: dict[_Any, DType] = {
    DateTimeNaive: DATE_TIME_NAIVE,
    DateTimeUtc: DATE_TIME_UTC,
    Duration: DURATION,
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    np.ndarray: ANY_ARRAY,
    _Any: ANY,
    dict: JSON,
}


def wrap(input_type: _Any) -> DType:
    """Map a Python annotation / value-type to a DType."""
    if isinstance(input_type, DType):
        return input_type
    if input_type in _FROM_PY:
        return _FROM_PY[input_type]
    origin = typing.get_origin(input_type)
    args = typing.get_args(input_type)
    import types as _types

    if origin is typing.Union or origin is _types.UnionType:
        # typing.Optional[X] AND PEP-604 `X | None` literals (their
        # origin is types.UnionType, which the old string compare against
        # "types.UnionType" never matched — repr is "<class ...>")
        non_none = [a for a in args if a is not type(None)]
        has_none = len(non_none) != len(args)
        if len(non_none) == 1:
            inner = wrap(non_none[0])
        else:
            inner = ANY
        return Optional(inner) if has_none else inner
    if origin in (tuple,):
        if len(args) == 2 and args[1] is Ellipsis:
            return List(wrap(args[0]))
        return Tuple(*[wrap(a) for a in args])
    if origin in (list,):
        return List(wrap(args[0]) if args else ANY)
    if origin in (dict,):
        return JSON
    from pathway_tpu.internals import keys

    if isinstance(input_type, type) and issubclass(input_type, keys.Pointer):
        return POINTER
    if input_type is np.ndarray:
        return ANY_ARRAY
    if callable(input_type) and input_type is not _Any:
        # typing constructs we don't model precisely
        return ANY
    return ANY


def unoptionalize(dtype: DType) -> DType:
    return dtype.strip_optional()


def dtype_of_value(value: _Any) -> DType:
    from pathway_tpu.internals import keys
    from pathway_tpu.internals.json import Json

    if value is None:
        return NONE
    if isinstance(value, bool):
        return BOOL
    if isinstance(value, int):
        return INT
    if isinstance(value, float):
        return FLOAT
    if isinstance(value, str):
        return STR
    if isinstance(value, bytes):
        return BYTES
    if isinstance(value, keys.Pointer):
        return POINTER
    if isinstance(value, Json):
        return JSON
    if isinstance(value, datetime.timedelta):
        return DURATION
    if isinstance(value, datetime.datetime):
        return DATE_TIME_UTC if value.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(value, tuple):
        return Tuple(*[dtype_of_value(v) for v in value])
    if isinstance(value, np.ndarray):
        return Array(value.ndim, INT if value.dtype.kind == "i" else FLOAT)
    if isinstance(value, dict):
        return JSON
    if callable(value):
        return Callable()
    return ANY


_NUMERIC_ORDER = {BOOL: 0, INT: 1, FLOAT: 2}


def is_subtype(sub: DType, sup: DType) -> bool:
    """Lattice ordering ``sub <= sup`` (reference ``dtype.is_subclass`` /
    the ``dtypes_pairs`` relation): INT <= FLOAT, T <= Optional(T),
    NONE <= Optional(T), covariant Tuple/List/Array, everything <= ANY."""
    if sup == ANY or sub == sup:
        return True
    if isinstance(sup, Optional):
        if sub == NONE:
            return True
        return is_subtype(sub.strip_optional() if isinstance(sub, Optional) else sub, sup.wrapped)
    if isinstance(sub, Optional):
        return False  # Optional(T) </= non-optional
    if sub in _NUMERIC_ORDER and sup in _NUMERIC_ORDER:
        return _NUMERIC_ORDER[sub] <= _NUMERIC_ORDER[sup]
    if isinstance(sub, Tuple) and isinstance(sup, Tuple):
        return len(sub.element_types) == len(sup.element_types) and all(
            is_subtype(s, t)
            for s, t in zip(sub.element_types, sup.element_types)
        )
    if isinstance(sub, Tuple) and isinstance(sup, List):
        return all(is_subtype(s, sup.element_type) for s in sub.element_types)
    if isinstance(sub, List) and isinstance(sup, List):
        return is_subtype(sub.element_type, sup.element_type)
    if isinstance(sub, Array) and isinstance(sup, Array):
        dim_ok = sup.n_dim is None or sub.n_dim == sup.n_dim
        return dim_ok and is_subtype(sub.wrapped, sup.wrapped)
    if isinstance(sub, Future) and isinstance(sup, Future):
        return is_subtype(sub.wrapped, sup.wrapped)
    return False


def types_lca(a: DType, b: DType) -> DType:
    """Least common ancestor in the lattice (reference ``dtype.types_lca``):
    the narrowest type both sides convert to, structure-aware for
    Optional/Tuple/List/Array; ANY when unrelated."""
    if is_subtype(a, b):
        return b
    if is_subtype(b, a):
        return a
    if a == NONE:
        return Optional(b)
    if b == NONE:
        return Optional(a)
    if isinstance(a, Optional) or isinstance(b, Optional):
        return Optional(types_lca(a.strip_optional(), b.strip_optional()))
    if a in _NUMERIC_ORDER and b in _NUMERIC_ORDER:
        return a if _NUMERIC_ORDER[a] >= _NUMERIC_ORDER[b] else b
    if isinstance(a, Tuple) and isinstance(b, Tuple):
        if len(a.element_types) == len(b.element_types):
            return Tuple(
                *[
                    types_lca(x, y)
                    for x, y in zip(a.element_types, b.element_types)
                ]
            )
        return List(
            types_lca(
                lub_many(*a.element_types) if a.element_types else ANY,
                lub_many(*b.element_types) if b.element_types else ANY,
            )
        )
    if isinstance(a, (Tuple, List)) and isinstance(b, (Tuple, List)):
        ea = lub_many(*a.element_types) if isinstance(a, Tuple) else a.element_type
        eb = lub_many(*b.element_types) if isinstance(b, Tuple) else b.element_type
        return List(types_lca(ea, eb))
    if isinstance(a, Array) and isinstance(b, Array):
        return Array(
            a.n_dim if a.n_dim == b.n_dim else None,
            types_lca(a.wrapped, b.wrapped),
        )
    return ANY


def lub(a: DType, b: DType) -> DType:
    """Least upper bound of two dtypes (used by if_else/concat/coalesce)."""
    return types_lca(a, b)


def lub_many(*dtypes: DType) -> DType:
    out = dtypes[0]
    for d in dtypes[1:]:
        out = lub(out, d)
    return out


def coerce(value: _Any, dtype: DType) -> _Any:
    """Best-effort runtime coercion of a parsed value to ``dtype``."""
    if value is None:
        return None
    base = dtype.strip_optional()
    try:
        if base == FLOAT and isinstance(value, int):
            return float(value)
        if base == INT and isinstance(value, float) and value.is_integer():
            return int(value)
        if base == STR and not isinstance(value, str):
            return str(value)
        if base == BOOL and isinstance(value, str):
            return value.lower() in ("true", "1", "t", "yes")
        if base == INT and isinstance(value, str):
            return int(value)
        if base == FLOAT and isinstance(value, str):
            return float(value)
    except (ValueError, TypeError):
        return value
    return value


@dataclass(frozen=True)
class ColumnProperties:
    """Per-column engine properties (reference ``TableProperties``,
    ``src/engine/graph.rs:374``)."""

    dtype: DType
    append_only: bool = False
