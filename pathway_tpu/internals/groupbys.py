"""GroupedTable: ``table.groupby(...).reduce(...)``.

Capability parity with reference ``python/pathway/internals/groupbys.py``:
reduction over grouping columns with retraction-aware reducers, including
expressions that mix reducers with grouping columns
(``pw.reducers.sum(t.x) + pw.this.g``).
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys as K
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
    _wrap,
    smart_name,
)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.thisclass import this as THIS


class GroupedTable:
    def __init__(
        self,
        source: Any,
        grouping: list[ColumnExpression],
        set_id: bool = False,
    ):
        self._source = source
        self._grouping = grouping
        self._set_id = set_id
        for g in self._grouping:
            if not isinstance(g, ColumnReference):
                raise NotImplementedError(
                    "groupby currently supports column references as grouping keys; "
                    "select the computed expression into a column first"
                )

    def _match_grouping(self, ref: ColumnReference) -> int | None:
        for i, g in enumerate(self._grouping):
            assert isinstance(g, ColumnReference)
            same_table = g._table is ref._table or getattr(
                g._table, "_layout_token", object()
            ) is getattr(ref._table, "_layout_token", None)
            if same_table and g._name == ref._name:
                return i
        return None

    def reduce(self, *args: Any, **kwargs: Any) -> Any:
        from pathway_tpu.internals.table import Table

        source: Table = self._source
        named: list[tuple[str, ColumnExpression]] = []
        for a in args:
            e = _wrap(a)._substitute({THIS: source})
            n = smart_name(e)
            if n is None:
                raise ValueError(
                    "Positional reduce() arguments must be column references"
                )
            named.append((n, e))
        for n, a in kwargs.items():
            named.append((n, _wrap(a)._substitute({THIS: source})))

        # --- rewrite each output expression: reducers and grouping refs
        # become slots of the intermediate groupby output table
        reducer_slots: list[ReducerExpression] = []

        n_group = len(self._grouping)
        inter_names = [f"__g{i}" for i in range(n_group)]

        def alloc_reducer(e: ReducerExpression) -> int:
            reducer_slots.append(e)
            return len(reducer_slots) - 1

        inter_ref_holder: list[Any] = [None]

        def rewrite(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ReducerExpression):
                i = alloc_reducer(e)
                return ColumnReference(inter_ref_holder, f"__r{i}")
            if isinstance(e, ColumnReference):
                if e._name == "id" and self._match_grouping(e) is None:
                    # group key pointer
                    return ColumnReference(inter_ref_holder, "id")
                gi = self._match_grouping(e)
                if gi is None:
                    raise ValueError(
                        f"Column {e._name!r} must appear in groupby(...) or inside "
                        "a reducer"
                    )
                return ColumnReference(inter_ref_holder, f"__g{gi}")
            children = [rewrite(c) for c in e._children()]
            return e._rebuild(children)

        rewritten = [(n, rewrite(e)) for n, e in named]

        # --- build engine groupby
        layout = source._layout()
        gfns = [
            g._substitute({THIS: source})._compile(layout.resolver)
            for g in self._grouping
        ]

        if len(gfns) == 1:
            gfn0 = gfns[0]

            def group_fn(key: Any, values: tuple) -> tuple:
                return (gfn0((key, values)),)

        else:

            def group_fn(key: Any, values: tuple) -> tuple:
                kv = (key, values)
                return tuple(f(kv) for f in gfns)

        # native partial-aggregation spec: usable when every grouping key
        # and reducer argument is a plain positional column (the common
        # case); engine falls back to the compiled-closure loop otherwise
        fast_group: list[int] = []
        fast_ok = True
        for g in self._grouping:
            ge = g._substitute({THIS: source})
            pos = (
                layout.resolve_pos(ge) if isinstance(ge, ColumnReference) else None
            )
            if pos is None:
                fast_ok = False
                break
            fast_group.append(pos)
        fast_reds: list[tuple[int, tuple]] = []

        def _arg_positions(args: list) -> tuple | None:
            poses = []
            for a in args:
                if not isinstance(a, ColumnReference):
                    return None
                p = layout.resolve_pos(a)
                if p is None:
                    return None
                poses.append(p)
            return tuple(poses)

        reducer_args: list[tuple[Any, Callable]] = []
        for re_expr in reducer_slots:
            impl = re_expr._reducer.make_impl(**re_expr._reducer_kwargs)
            arg_fns = [a._compile(layout.resolver) for a in re_expr._args]
            if fast_ok:
                code = impl.native_code
                poses = _arg_positions(list(re_expr._args))
                if code is None or poses is None:
                    fast_ok = False
                elif code == 0:
                    fast_reds.append((0, ()))
                elif impl.name in ("argmin", "argmax") and len(poses) == 1:
                    fast_reds.append((code, (poses[0], -1)))  # (value, row key)
                else:
                    fast_reds.append((code, poses))
            if impl.name in ("argmin", "argmax"):
                # one arg: returns the extreme row's KEY (reference
                # semantics); two args: (sort_value, returned_value)
                if len(arg_fns) == 2:
                    def arg_fn(key, values, arg_fns=arg_fns):
                        kv = (key, values)
                        return (arg_fns[0](kv), arg_fns[1](kv))

                else:
                    def arg_fn(key, values, arg_fns=arg_fns):
                        kv = (key, values)
                        return (arg_fns[0](kv), key)

            elif not arg_fns:
                def arg_fn(key, values):
                    return ()

            elif len(arg_fns) == 1:
                def arg_fn(key, values, f0=arg_fns[0]):
                    return (f0((key, values)),)

            else:
                def arg_fn(key, values, arg_fns=arg_fns):
                    kv = (key, values)
                    return tuple(f(kv) for f in arg_fns)

            reducer_args.append((impl, arg_fn))

        # groupby(..., id=col): the group key VALUE (a pointer) becomes the
        # output row id (reference groupby id= semantics)
        output_key_fn = None
        if self._set_id:
            if len(self._grouping) != 1:
                raise ValueError("groupby(id=...) needs exactly one grouping column")
            output_key_fn = lambda gvals: gvals[0]  # noqa: E731
        node = eg.GroupByNode(
            G.engine_graph,
            source._node,
            group_fn,
            reducer_args,
            output_key_fn=output_key_fn,
            include_group_values=True,
            name="groupby",
            fast_spec=(tuple(fast_group), tuple(fast_reds)) if fast_ok else None,
        )
        grouping_names = [
            g._name for g in self._grouping if isinstance(g, ColumnReference)
        ]
        used: set[str] = set(grouping_names)
        for re_expr in reducer_slots:
            for a in re_expr._args:
                try:
                    for r in a._references():
                        if r._name != "id":
                            used.add(r._name)
                except Exception:
                    pass
        node.meta["groupby"] = {
            "grouping": grouping_names,
            "reducers": [impl.name for impl, _ in reducer_args],
        }
        node.meta["used_cols"] = sorted(used)
        inter_cols = inter_names + [f"__r{i}" for i in range(len(reducer_slots))]
        inter_dtypes: dict[str, dt.DType] = {}
        for i, g in enumerate(self._grouping):
            inter_dtypes[f"__g{i}"] = g._dtype
        for i, re_expr in enumerate(reducer_slots):
            inter_dtypes[f"__r{i}"] = re_expr._dtype
        inter = Table(node, inter_cols, inter_dtypes, name="groupby_inter")

        # Re-point rewritten references at the concrete intermediate table.
        def repoint(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ColumnReference) and e._table is inter_ref_holder:
                if e._name == "id":
                    return inter.id
                return ColumnReference(inter, e._name)
            children = [repoint(c) for c in e._children()]
            return e._rebuild(children)

        final = {n: repoint(e) for n, e in rewritten}
        return inter.select(**final)
