"""Text splitters (reference ``xpacks/llm/splitters.py:13-121``).

``TokenCountSplitter`` uses the framework tokenizer for counting (the
reference uses tiktoken, unavailable offline); chunk contract matches the
reference: ``list[tuple[text, metadata]]``.
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals.udfs import UDF

__all__ = ["null_splitter", "TokenCountSplitter"]


def null_splitter(txt: str) -> list[tuple[str, dict]]:
    """No-op splitter: one chunk (reference ``null_splitter``)."""
    return [(txt, {})]


_SENTENCE_END = re.compile(r"(?<=[.!?])\s+")


class TokenCountSplitter(UDF):
    """Split text into chunks of [min_tokens, max_tokens], preferring
    sentence boundaries (reference ``TokenCountSplitter``)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        from pathway_tpu.models.tokenizer import HashTokenizer

        self._tok = HashTokenizer()

    def _count(self, text: str) -> int:
        return self._tok.count_tokens(text)

    def __wrapped__(self, txt: str, **kwargs: Any) -> list[tuple[str, dict]]:
        text = str(txt)
        if not text.strip():
            return []
        pieces = _SENTENCE_END.split(text)
        chunks: list[str] = []
        cur = ""
        cur_tokens = 0
        for piece in pieces:
            pt = self._count(piece)
            if pt > self.max_tokens:
                # sentence longer than a chunk: hard-split by words
                if cur:
                    chunks.append(cur)
                    cur, cur_tokens = "", 0
                words = piece.split()
                step = max(self.max_tokens, 1)
                for s in range(0, len(words), step):
                    chunks.append(" ".join(words[s : s + step]))
                continue
            # max_tokens is a hard ceiling: close the chunk whenever adding
            # the next sentence would overflow it
            if cur and cur_tokens + pt > self.max_tokens:
                chunks.append(cur)
                cur, cur_tokens = piece, pt
            else:
                cur = f"{cur} {piece}".strip() if cur else piece
                cur_tokens += pt
        if cur:
            # a trailing fragment below min_tokens merges back only when the
            # combined chunk still respects max_tokens
            if (
                chunks
                and cur_tokens < self.min_tokens
                and self._count(chunks[-1]) + cur_tokens <= self.max_tokens
            ):
                chunks[-1] = f"{chunks[-1]} {cur}"
            else:
                chunks.append(cur)
        return [(c, {}) for c in chunks]
