"""Shared typing for the LLM xpack (reference
``python/pathway/xpacks/llm/_typing.py``)."""

from __future__ import annotations

from typing import Any, Callable, TypedDict


class Doc(TypedDict, total=False):
    """A document chunk flowing through the RAG pipeline."""

    text: str
    metadata: dict
    score: float


#: a UDF / callable mapping list[Doc] -> list[Doc] (parsers, splitters,
#: post-processors, rerank filters)
DocTransformerCallable = Callable[[list[Doc]], list[Doc]]

DocTransformer = Any  # UDF or DocTransformerCallable
