"""Chat model wrappers (reference ``xpacks/llm/llms.py``).

``BaseChat`` (reference ``llms.py:27``) is the UDF contract:
``__wrapped__(messages) -> str`` where messages is a list of
``{"role": ..., "content": ...}`` dicts.  Network chats
(OpenAI/LiteLLM/Cohere, reference ``:84/:313/:544``) are gated on their
client packages; :class:`HFPipelineChat` (``:441``) on a locally cached
model.  ``prompt_chat_single_qa`` matches the reference helper.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import udfs
from pathway_tpu.internals.udfs import UDF

__all__ = [
    "BaseChat",
    "OpenAIChat",
    "LiteLLMChat",
    "HFPipelineChat",
    "CohereChat",
    "prompt_chat_single_qa",
]


def prompt_chat_single_qa(question: str) -> list[dict]:
    """Wrap a plain question into the single-turn message format
    (reference ``llms.py prompt_chat_single_qa``)."""
    return [{"role": "user", "content": str(question)}]


class BaseChat(UDF):
    """Base chat UDF (reference ``llms.py:27``)."""

    def __init__(
        self,
        *,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        **call_kwargs: Any,
    ):
        executor = (
            udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
            if (capacity is not None or retry_strategy is not None)
            else None
        )
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.model = model
        self.call_kwargs = call_kwargs

    def _accepts_call_arg(self, arg: str) -> bool:
        return True


class _GatedChat(BaseChat):
    _client_pkg = ""

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        try:
            __import__(self._client_pkg)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} needs the {self._client_pkg!r} package "
                "(and network access)"
            ) from e


class OpenAIChat(_GatedChat):
    """reference ``llms.py:84``"""

    _client_pkg = "openai"

    async def __wrapped__(self, messages: list[dict], **kwargs: Any) -> str | None:
        import openai

        client = openai.AsyncOpenAI()
        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        ret = await client.chat.completions.create(messages=messages, **kw)
        return ret.choices[0].message.content


class LiteLLMChat(_GatedChat):
    """reference ``llms.py:313``"""

    _client_pkg = "litellm"

    async def __wrapped__(self, messages: list[dict], **kwargs: Any) -> str | None:
        import litellm

        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        ret = await litellm.acompletion(messages=messages, **kw)
        return ret.choices[0]["message"]["content"]


class CohereChat(_GatedChat):
    """reference ``llms.py:544``"""

    _client_pkg = "cohere"

    async def __wrapped__(self, messages: list[dict], **kwargs: Any) -> str | None:
        import cohere

        client = cohere.AsyncClient()
        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        query = messages[-1]["content"]
        ret = await client.chat(message=query, **kw)
        return ret.text


class HFPipelineChat(BaseChat):
    """Local HuggingFace text-generation pipeline (reference ``llms.py:441``;
    torch-cpu). Requires a locally cached model — no downloads attempted."""

    def __init__(self, model: str | None = None, device: str = "cpu", **kwargs: Any):
        super().__init__(model=model, **kwargs)
        from transformers import pipeline

        self.pipeline = pipeline(
            "text-generation",
            model=model,
            device=device,
            model_kwargs={"local_files_only": True},
        )

    def __wrapped__(self, messages: list[dict] | str, **kwargs: Any) -> str | None:
        if isinstance(messages, str):
            prompt = messages
        else:
            prompt = "\n".join(m.get("content", "") for m in messages)
        out = self.pipeline(prompt, **{**self.call_kwargs, **kwargs})
        text = out[0]["generated_text"]
        return text[len(prompt) :] if text.startswith(prompt) else text
