"""Prompt templates (reference ``xpacks/llm/prompts.py``)."""

from __future__ import annotations

from pathway_tpu.internals.udfs import udf

__all__ = [
    "prompt_qa",
    "prompt_short_qa",
    "prompt_citing_qa",
    "prompt_summarize",
    "prompt_query_rewrite",
    "prompt_qa_geometric_rag",
]


def _docs_text(docs: list) -> str:
    parts = []
    for d in docs:
        if isinstance(d, dict):
            parts.append(str(d.get("text", d)))
        else:
            parts.append(str(d))
    return "\n\n".join(parts)


NO_INFO = "No information found."


def prompt_qa_geometric_rag(
    query: str,
    docs: list,
    information_not_found_response: str = NO_INFO,
    additional_rules: str = "",
) -> str:
    """Plain-function QA template (used directly inside the adaptive RAG
    loop, reference ``answer_with_geometric_rag_strategy``)."""
    return (
        "Use the below documents to answer the question. If the documents "
        f"do not contain the answer, reply exactly: {information_not_found_response}"
        f"{additional_rules}\n\nDocuments:\n{_docs_text(docs)}\n\n"
        f"Question: {query}\nAnswer:"
    )


#: the same template as a column UDF
prompt_qa = udf(prompt_qa_geometric_rag)


@udf
def prompt_short_qa(query: str, docs: list) -> str:
    return (
        "Answer the question with a short phrase based only on the documents. "
        f"If unknown, reply exactly: {NO_INFO}\n\n"
        f"Documents:\n{_docs_text(docs)}\n\nQuestion: {query}\nAnswer:"
    )


@udf
def prompt_citing_qa(query: str, docs: list) -> str:
    numbered = "\n\n".join(
        f"[{i + 1}] {d.get('text', d) if isinstance(d, dict) else d}"
        for i, d in enumerate(docs)
    )
    return (
        "Answer based on the numbered documents, citing sources like [1]. "
        f"If the answer is not present, reply exactly: {NO_INFO}\n\n"
        f"{numbered}\n\nQuestion: {query}\nAnswer:"
    )


@udf
def prompt_summarize(text_list: list) -> str:
    joined = "\n".join(str(t) for t in text_list)
    return f"Summarize the following texts into a single concise summary:\n\n{joined}\n\nSummary:"


@udf
def prompt_query_rewrite(query: str) -> str:
    return (
        "Rewrite the following user question as a concise search query, "
        f"keeping all key entities:\n\n{query}\n\nSearch query:"
    )


