"""Built-in HTML and DOCX text extraction (no external dependencies).

The reference delegates rich-document partitioning to the
``unstructured`` package (``python/pathway/xpacks/llm/parsers.py:79``);
that package (and its system deps) are unavailable here, so the two most
common rich formats get native extractors in the spirit of the built-in
PDF extractor (``_pdf.py``):

- HTML via :mod:`html.parser` — block-level segmentation with
  unstructured-style element categories (``Title`` for headings,
  ``ListItem`` for ``li``, ``Table`` rows joined per table,
  ``NarrativeText`` otherwise); ``script``/``style`` dropped.
- DOCX via :mod:`zipfile` + :mod:`xml.etree` over ``word/document.xml``
  (a DOCX is a zip of WordprocessingML): paragraphs join their ``w:t``
  runs, ``Heading*`` paragraph styles map to ``Title``, list paragraphs
  (``w:numPr``) to ``ListItem``, and each ``w:tbl`` becomes one
  ``Table`` element with tab-separated cells.

Both return ``list[(text, metadata)]`` blocks; metadata carries the
element ``category`` so DocumentStore chunk filters can use it.
"""

from __future__ import annotations

import io
import re
import zipfile
from html.parser import HTMLParser
from typing import Any
from xml.etree import ElementTree

__all__ = [
    "extract_html_blocks",
    "extract_docx_blocks",
    "sniff_format",
]

_BLOCK_TAGS = {
    "p", "div", "section", "article", "li", "blockquote", "pre",
    "h1", "h2", "h3", "h4", "h5", "h6", "tr", "br", "td", "th",
}
_HEADINGS = {"h1", "h2", "h3", "h4", "h5", "h6"}
_SKIP_TAGS = {"script", "style", "head", "noscript", "template"}


class _HtmlBlocks(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.blocks: list[tuple[str, dict]] = []
        self._buf: list[str] = []
        self._category = "NarrativeText"
        self._skip_depth = 0
        self._in_table = 0
        self._table_rows: list[str] = []
        self.title: str | None = None
        self._in_title = False

    def _flush(self) -> None:
        text = re.sub(r"\s+", " ", "".join(self._buf)).strip()
        self._buf = []
        category = self._category
        # reset BEFORE the empty-text return: an empty <h1></h1> must not
        # leak Title onto the following paragraph
        self._category = "NarrativeText"
        if not text:
            return
        if self._in_table:
            self._table_rows.append(text)
        else:
            self.blocks.append((text, {"category": category}))

    def handle_starttag(self, tag: str, attrs: Any) -> None:
        if tag in _SKIP_TAGS:
            self._skip_depth += 1
            return
        if tag == "title":
            self._in_title = True
            return
        if tag == "table":
            self._flush()
            self._in_table += 1
            return
        if tag in _BLOCK_TAGS:
            if tag in ("td", "th"):
                self._buf.append("\t")
                return
            self._flush()
            if tag in _HEADINGS:
                self._category = "Title"
            elif tag == "li":
                self._category = "ListItem"

    def handle_endtag(self, tag: str) -> None:
        if tag in _SKIP_TAGS:
            self._skip_depth = max(0, self._skip_depth - 1)
            return
        if tag == "title":
            self._in_title = False
            return
        if tag == "table":
            self._flush()
            self._in_table = max(0, self._in_table - 1)
            if not self._in_table and self._table_rows:
                self.blocks.append(
                    ("\n".join(self._table_rows), {"category": "Table"})
                )
                self._table_rows = []
            return
        if tag in _BLOCK_TAGS and tag not in ("td", "th", "br"):
            self._flush()

    def handle_data(self, data: str) -> None:
        if self._in_title:  # <title> lives inside the skipped <head>
            self.title = (self.title or "") + data.strip()
            return
        if self._skip_depth:
            return
        self._buf.append(data)


def extract_html_blocks(data: bytes | str) -> list[tuple[str, dict]]:
    """Block-segmented text of an HTML document with element categories."""
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    p = _HtmlBlocks()
    p.feed(data)
    p.close()
    p._flush()
    for _text, meta in p.blocks:
        meta["filetype"] = "text/html"
        if p.title:
            meta["page_title"] = p.title
    return p.blocks


_W_NS = "{http://schemas.openxmlformats.org/wordprocessingml/2006/main}"


def _docx_paragraph_text(par: Any) -> str:
    parts: list[str] = []
    for node in par.iter():
        if node.tag == f"{_W_NS}t" and node.text:
            parts.append(node.text)
        elif node.tag in (f"{_W_NS}tab",):
            parts.append("\t")
        elif node.tag in (f"{_W_NS}br", f"{_W_NS}cr"):
            parts.append("\n")
    return "".join(parts)


def _docx_paragraph_category(par: Any) -> str:
    ppr = par.find(f"{_W_NS}pPr")
    if ppr is not None:
        style = ppr.find(f"{_W_NS}pStyle")
        if style is not None:
            val = style.get(f"{_W_NS}val", "")
            if val.lower().startswith(("heading", "title")):
                return "Title"
        if ppr.find(f"{_W_NS}numPr") is not None:
            return "ListItem"
    return "NarrativeText"


def extract_docx_blocks(data: bytes) -> list[tuple[str, dict]]:
    """Paragraph/table blocks of a DOCX file with element categories."""
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        xml = zf.read("word/document.xml")
    root = ElementTree.fromstring(xml)
    body = root.find(f"{_W_NS}body")
    if body is None:
        return []
    blocks: list[tuple[str, dict]] = []
    for child in body:
        if child.tag == f"{_W_NS}p":
            text = _docx_paragraph_text(child).strip()
            if text:
                blocks.append(
                    (
                        text,
                        {
                            "category": _docx_paragraph_category(child),
                            "filetype": (
                                "application/vnd.openxmlformats-officedocument"
                                ".wordprocessingml.document"
                            ),
                        },
                    )
                )
        elif child.tag == f"{_W_NS}tbl":
            rows: list[str] = []
            for tr in child.iter(f"{_W_NS}tr"):
                cells = [
                    " ".join(
                        _docx_paragraph_text(p).strip()
                        for p in tc.iter(f"{_W_NS}p")
                    ).strip()
                    for tc in tr.findall(f"{_W_NS}tc")
                ]
                row = "\t".join(c for c in cells if c)
                if row:
                    rows.append(row)
            if rows:
                blocks.append(
                    (
                        "\n".join(rows),
                        {
                            "category": "Table",
                            "filetype": (
                                "application/vnd.openxmlformats-officedocument"
                                ".wordprocessingml.document"
                            ),
                        },
                    )
                )
    return blocks


def sniff_format(data: bytes) -> str:
    """Best-effort content sniffing: 'pdf' | 'docx' | 'html' | 'text'."""
    head = data[:2048].lstrip()
    if head.startswith(b"%PDF"):
        return "pdf"
    if data[:2] == b"PK":
        try:
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                if "word/document.xml" in zf.namelist():
                    return "docx"
        except zipfile.BadZipFile:
            pass
        return "text"
    low = head[:256].lower()
    if low.startswith(b"<!doctype html") or b"<html" in low or (
        low.startswith(b"<") and b"<body" in head.lower()
    ):
        return "html"
    return "text"
