"""RAG quality evaluation harness.

Offline analogue of the reference's RAGAS-based eval suite
(``integration_tests/rag_evals/``: ``evaluator.py``, ``ragas_utils.py``,
``test_eval.py``): given a dataset of (question, expected answer,
relevant doc ids), run the retrieval stack end-to-end and score

- **retrieval recall@k** — fraction of questions whose relevant doc(s)
  appear in the top-k retrieved set (the RAGAS "context recall" axis);
- **answer token F1** — token overlap between the produced and expected
  answers (the "answer correctness" axis, no judge LLM needed offline);
- **reranker lift** — recall@k after cross-encoder reranking minus
  recall@k of raw vector order (is the reranker helping?).

No external services: metrics are plain functions over retrieved doc
lists / answer strings, so the same harness scores real OpenAI-backed
pipelines when credentials exist.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Sequence

__all__ = [
    "RagEvalItem",
    "RagEvalReport",
    "answer_token_f1",
    "recall_at_k",
    "evaluate_retrieval",
]

_TOKEN_RE = re.compile(r"[a-z0-9]+")


@dataclasses.dataclass
class RagEvalItem:
    """One dataset row: a question, the doc ids that answer it, and
    (optionally) the expected answer text."""

    question: str
    relevant_docs: frozenset
    expected_answer: str | None = None

    def __init__(self, question, relevant_docs, expected_answer=None):
        self.question = question
        self.relevant_docs = frozenset(relevant_docs)
        self.expected_answer = expected_answer


@dataclasses.dataclass
class RagEvalReport:
    recall_at_k: float
    k: int
    per_question: list[dict]
    answer_f1: float | None = None

    def __str__(self) -> str:
        parts = [f"recall@{self.k}={self.recall_at_k:.3f}"]
        if self.answer_f1 is not None:
            parts.append(f"answer_f1={self.answer_f1:.3f}")
        return " ".join(parts)


def _tokens(text: str) -> list[str]:
    return _TOKEN_RE.findall(text.lower())


def answer_token_f1(produced: str, expected: str) -> float:
    """Token-overlap F1 between a produced and an expected answer."""
    p, e = _tokens(produced), _tokens(expected)
    if not p or not e:
        return float(p == e)
    common: dict[str, int] = {}
    pe = {}
    for t in p:
        pe[t] = pe.get(t, 0) + 1
    matched = 0
    for t in e:
        if pe.get(t, 0) > 0:
            pe[t] -= 1
            matched += 1
    if matched == 0:
        return 0.0
    precision = matched / len(p)
    recall = matched / len(e)
    return 2 * precision * recall / (precision + recall)


def recall_at_k(
    retrieved: Sequence[Sequence[Any]],
    relevant: Sequence[frozenset],
    k: int,
) -> float:
    """Fraction of questions with at least one relevant doc in the top-k."""
    if not retrieved:
        return 0.0
    hits = 0
    for got, want in zip(retrieved, relevant):
        if want & set(list(got)[:k]):
            hits += 1
    return hits / len(retrieved)


def evaluate_retrieval(
    items: Sequence[RagEvalItem],
    retrieve: Callable[[str, int], "list[Any]"],
    *,
    k: int = 3,
    answer: Callable[[str], str] | None = None,
) -> RagEvalReport:
    """Run every question through ``retrieve(question, k) -> [doc_id,...]``
    (and optionally ``answer(question) -> str``), score the dataset."""
    per_q = []
    retrieved_all = []
    f1s = []
    for item in items:
        got = list(retrieve(item.question, k))
        retrieved_all.append(got)
        row: dict[str, Any] = {
            "question": item.question,
            "retrieved": got,
            "hit": bool(item.relevant_docs & set(got[:k])),
        }
        if answer is not None and item.expected_answer is not None:
            produced = answer(item.question)
            row["answer"] = produced
            row["answer_f1"] = answer_token_f1(produced, item.expected_answer)
            f1s.append(row["answer_f1"])
        per_q.append(row)
    return RagEvalReport(
        recall_at_k=recall_at_k(
            retrieved_all, [i.relevant_docs for i in items], k
        ),
        k=k,
        per_question=per_q,
        answer_f1=(sum(f1s) / len(f1s)) if f1s else None,
    )
