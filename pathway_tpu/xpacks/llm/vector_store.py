"""VectorStoreServer / VectorStoreClient
(reference ``xpacks/llm/vector_store.py:39-766``).

The server is DocumentStore + REST routes with embedding done inside the
server (TPU-batched); the client is a thin HTTP wrapper.  LangChain /
LlamaIndex adapter constructors keep the reference API shape.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Any, Callable

from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import (
    BruteForceKnnFactory,
    InnerIndexFactory,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.servers import DocumentStoreServer

__all__ = ["VectorStoreServer", "VectorStoreClient"]


class VectorStoreServer:
    """reference ``vector_store.py:39``"""

    def __init__(
        self,
        *docs: Table,
        embedder: Any = None,
        parser: Any = None,
        splitter: Any = None,
        doc_post_processors: list[Callable] | None = None,
        index_factory: InnerIndexFactory | None = None,
        reserved_space: int = 1024,
        mesh: Any = None,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
    ):
        if embedder is None and index_factory is None:
            from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder

            embedder = TPUEncoderEmbedder()
        if index_factory is None:
            # delta_cap/tombstone_fraction/auto_merge tune the live index
            # maintenance layer (delta segment + background merge) the
            # built index runs under; see stdlib/indexing/segments.py
            index_factory = BruteForceKnnFactory(
                embedder=embedder,
                reserved_space=reserved_space,
                mesh=mesh,
                delta_cap=delta_cap,
                tombstone_fraction=tombstone_fraction,
                auto_merge=auto_merge,
            )
        self.docs = docs
        self.document_store = DocumentStore(
            list(docs),
            retriever_factory=index_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
        )
        self._server: DocumentStoreServer | None = None

    @classmethod
    def from_langchain_components(
        cls, *docs: Table, embedder: Any, splitter: Any = None, **kwargs: Any
    ) -> "VectorStoreServer":
        """reference ``vector_store.py:93``"""
        from pathway_tpu.internals.udfs import udf

        @udf
        def lc_embed(text: str) -> Any:
            return embedder.embed_documents([text])[0]

        lc_split = None
        if splitter is not None:

            @udf
            def lc_split(text: str) -> list[tuple[str, dict]]:  # noqa: F811
                return [(c, {}) for c in splitter.split_text(text)]

        factory = BruteForceKnnFactory(embedder=lc_embed)
        return cls(*docs, index_factory=factory, splitter=lc_split, **kwargs)

    @classmethod
    def from_llamaindex_components(
        cls, *docs: Table, transformations: list, **kwargs: Any
    ) -> "VectorStoreServer":
        """Build from a llama_index transformation pipeline (reference
        ``vector_store.py:137``).  Duck-typed like the langchain adapter —
        no llama_index import: the embedding component is recognised by
        ``get_text_embedding`` (BaseEmbedding protocol), text splitters by
        ``split_text`` (NodeParser/TextSplitter protocol)."""
        from pathway_tpu.internals.udfs import udf

        embed_component = None
        split_components = []
        for tr in transformations:
            if hasattr(tr, "get_text_embedding"):
                if embed_component is not None:
                    raise ValueError(
                        "transformations contain more than one embedding "
                        "component (get_text_embedding)"
                    )
                embed_component = tr
            elif hasattr(tr, "split_text"):
                split_components.append(tr)
            else:
                raise ValueError(
                    f"unsupported llama_index transformation {tr!r}: expected "
                    "an embedding (get_text_embedding) or a text splitter "
                    "(split_text)"
                )
        if embed_component is None:
            raise ValueError(
                "transformations must include an embedding component "
                "(get_text_embedding)"
            )

        @udf
        def li_embed(text: str) -> Any:
            return embed_component.get_text_embedding(text)

        li_split = None
        if split_components:

            @udf
            def li_split(text: str) -> list[tuple[str, dict]]:  # noqa: F811
                chunks = [text]
                for sp in split_components:  # chained splitters, in order
                    chunks = [c for ch in chunks for c in sp.split_text(ch)]
                return [(c, {}) for c in chunks]

        factory = BruteForceKnnFactory(embedder=li_embed)
        return cls(*docs, index_factory=factory, splitter=li_split, **kwargs)

    def run_server(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        *,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = False,
        admission: Any = None,
        tenant_field: str = "tenant",
    ) -> threading.Thread | None:
        """reference ``vector_store.py:478``; ``admission`` bounds the
        ingress per tenant (serving/admission.py) — full queues shed with
        429 + Retry-After instead of buffering unboundedly."""
        self._server = DocumentStoreServer(
            host,
            port,
            self.document_store,
            admission=admission,
            tenant_field=tenant_field,
        )
        return self._server.run(threaded=threaded, with_cache=with_cache)


class VectorStoreClient:
    """reference ``vector_store.py:651``"""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: float = 60,
    ):
        if url is None:
            if port is None:
                raise ValueError("VectorStoreClient needs a port (or a full url)")
            url = f"http://{host or '127.0.0.1'}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict) -> Any:
        req = urllib.request.Request(
            self.url + route,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def query(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(
        self,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        return self._post(
            "/v1/inputs",
            {
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )
