"""Rerankers (reference ``xpacks/llm/rerankers.py``).

TPU re-design: :class:`CrossEncoderReranker` (reference ``:186-235``,
per-row torch ``CrossEncoder.predict``) runs the flax cross-encoder as an
epoch-batched jitted call; :class:`EncoderReranker` (``:251``) scores with
the bi-encoder dot product.  ``rerank_topk_filter`` (``:15``) and
:class:`LLMReranker` (``:58``) are faithful ports of the host logic.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.internals.udfs import UDF, udf

__all__ = [
    "rerank_topk_filter",
    "CrossEncoderReranker",
    "EncoderReranker",
    "LLMReranker",
    "FlashRankReranker",
]


@udf
def rerank_topk_filter(
    docs: list[dict], scores: list[float], k: int = 5
) -> tuple[list[dict], list[float]]:
    """Keep the k best (docs, scores) pairs (reference ``rerankers.py:15``)."""
    order = np.argsort(-np.asarray(scores, dtype=np.float64))[: int(k)]
    return [docs[i] for i in order], [float(scores[i]) for i in order]


class CrossEncoderReranker(UDF):
    """(doc, query) -> relevance score via the TPU cross-encoder."""

    def __init__(
        self,
        model_name: str = "BAAI/bge-reranker-base",
        *,
        mesh: Any = None,
        params: Any = None,
        config: Any = None,
        max_batch_size: int | None = 256,
        **kwargs: Any,
    ):
        super().__init__(max_batch_size=max_batch_size, **kwargs)
        import os

        from pathway_tpu.models import BGE_RERANKER_BASE
        from pathway_tpu.parallel import JittedEncoder

        checkpoint_dir = model_name if os.path.isdir(model_name) else None
        if config is None:
            cfg = None if checkpoint_dir else BGE_RERANKER_BASE
        else:
            cfg = config
        self.encoder = JittedEncoder(
            cfg, cross=True, mesh=mesh, model_name=model_name, params=params,
            max_batch=max_batch_size or 256, checkpoint_dir=checkpoint_dir,
        )

    def __batch__(self, docs: list, queries: list) -> list[float]:
        texts = [d["text"] if isinstance(d, dict) else str(d) for d in docs]
        scores = self.encoder.score_pairs([str(q) for q in queries], texts)
        return [float(s) for s in scores]

    def __wrapped__(self, doc: Any, query: str) -> float:
        return self.__batch__([doc], [query])[0]


class EncoderReranker(UDF):
    """Bi-encoder similarity reranker (reference ``rerankers.py:251``)."""

    def __init__(self, embedder: Any = None, model_name: str = "all-MiniLM-L6-v2", **kwargs: Any):
        super().__init__(**kwargs)
        if embedder is None:
            from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder

            embedder = TPUEncoderEmbedder(model_name)
        self.embedder = embedder

    def __batch__(self, docs: list, queries: list) -> list[float]:
        texts = [d["text"] if isinstance(d, dict) else str(d) for d in docs]
        demb = np.stack(
            [np.asarray(v) for v in self.embedder._embed_batch(texts)]
        )
        qemb = np.stack(
            [np.asarray(v) for v in self.embedder._embed_batch([str(q) for q in queries])]
        )
        return [float(x) for x in np.sum(demb * qemb, axis=1)]

    def __wrapped__(self, doc: Any, query: str) -> float:
        return self.__batch__([doc], [query])[0]


class LLMReranker(UDF):
    """Chat-based 1-5 relevance scoring (reference ``rerankers.py:58``)."""

    PROMPT = (
        "Given a query and a document, rate how relevant the document is "
        "to the query on an integer scale of 1 to 5. Answer with ONLY the "
        "number.\nQuery: {query}\nDocument: {doc}"
    )

    def __init__(self, llm: Any, **kwargs: Any):
        super().__init__(**kwargs)
        self.llm = llm

    def __wrapped__(self, doc: Any, query: str) -> float:
        text = doc["text"] if isinstance(doc, dict) else str(doc)
        msg = [{"role": "user", "content": self.PROMPT.format(query=query, doc=text)}]
        fun = self.llm.__wrapped__ if hasattr(self.llm, "__wrapped__") else self.llm
        import inspect

        out = fun(msg)
        if inspect.isawaitable(out):
            import asyncio

            out = asyncio.run(out)
        try:
            return float(str(out).strip().split()[0])
        except (ValueError, IndexError):
            return 1.0


class FlashRankReranker(UDF):
    """reference ``rerankers.py:319`` — gated on the flashrank package."""

    def __init__(self, model: str = "ms-marco-TinyBERT-L-2-v2", **kwargs: Any):
        super().__init__(**kwargs)
        try:
            import flashrank  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "FlashRankReranker needs the 'flashrank' package; use "
                "CrossEncoderReranker (TPU) instead"
            ) from e
