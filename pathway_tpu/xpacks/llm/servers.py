"""REST servers for RAG apps (reference ``xpacks/llm/servers.py:16-272``)."""

from __future__ import annotations

import threading
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.io.http import PathwayWebserver, rest_connector

__all__ = [
    "BaseRestServer",
    "DocumentStoreServer",
    "QARestServer",
    "QASummaryRestServer",
]


class BaseRestServer:
    """Route registry over one webserver (reference ``servers.py:16``).

    ``admission`` (optional) is a serving-layer admission controller
    (``pathway_tpu/serving/admission.py``): every route this server
    registers admits requests against the tenant named by the payload's
    ``tenant_field`` before they enter the engine — a full tenant queue
    sheds with 429 + ``Retry-After`` instead of buffering unboundedly."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        admission: Any = None,
        tenant_field: str = "tenant",
        **kwargs: Any,
    ):
        self.host = host
        self.port = port
        self.admission = admission
        self.tenant_field = tenant_field
        self.webserver = PathwayWebserver(host=host, port=port)

    def serve(
        self,
        route: str,
        schema: Any,
        handler: Callable[[Table], Table],
        **kwargs: Any,
    ) -> None:
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            delete_completed_queries=kwargs.get("delete_completed_queries", False),
            admission=kwargs.get("admission", self.admission),
            tenant_field=kwargs.get("tenant_field", self.tenant_field),
        )
        writer(handler(queries))

    def serve_callable(
        self,
        route: str,
        schema: Any = None,
        callable_func: Callable | None = None,
        retry_strategy: Any = None,
        cache_strategy: Any = None,
        **additional_endpoint_kwargs: Any,
    ) -> Callable:
        """Expose an arbitrary Python callable (sync or async) as a REST
        endpoint (reference ``xpacks/llm/servers.py:227-272``).

        Each request row runs through an :class:`AsyncTransformer`, so a
        slow or async callable never blocks the engine loop; the HTTP
        response is the callable's return value.  When ``schema`` is
        omitted it is inferred from the callable's argument names (each
        argument becomes a JSON-typed request field).  Usable directly or
        as a decorator::

            @server.serve_callable("/v1/my_fn")
            async def my_fn(query: str): ...
        """
        from pathway_tpu.internals.json import Json
        from pathway_tpu.stdlib.utils.async_transformer import (
            AsyncTransformer,
            coerce_async,
        )

        def decorator(fn: Callable) -> Callable:
            use_schema = schema
            if use_schema is None:
                import inspect

                names = [
                    p.name
                    for p in inspect.signature(fn).parameters.values()
                    if p.kind
                    in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
                ]
                use_schema = pw.schema_from_types(**{n: object for n in names})
            async_fn = coerce_async(fn)

            class FuncAsyncTransformer(AsyncTransformer):
                output_schema = pw.schema_from_types(result=object)

                async def invoke(self, **kwargs: Any) -> dict:
                    kwargs = {
                        k: (
                            v.value
                            if isinstance(v, (Json, pw.PyObjectWrapper))
                            else v
                        )
                        for k, v in kwargs.items()
                    }
                    return {"result": await async_fn(**kwargs)}

            def handler(table: Table) -> Table:
                return (
                    FuncAsyncTransformer(input_table=table)
                    .with_options(
                        retry_strategy=retry_strategy,
                        cache_strategy=cache_strategy,
                    )
                    .successful
                )

            self.serve(route, use_schema, handler, **additional_endpoint_kwargs)
            return fn

        if callable_func is None:
            return decorator
        return decorator(callable_func)

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = False,
        **kwargs: Any,
    ) -> threading.Thread | None:
        """Start the engine (reference ``servers.py:58`` ``run``)."""
        if threaded:
            t = threading.Thread(target=pw.run, daemon=True, name="pw_server")
            t.start()
            return t
        pw.run()
        return None

    run_server = run


class DocumentStoreServer(BaseRestServer):
    """reference ``servers.py:92`` — exposes a DocumentStore over REST:
    /v1/retrieve, /v1/statistics, /v1/inputs."""

    def __init__(self, host: str, port: int, document_store: Any, **kwargs: Any):
        super().__init__(host, port, **kwargs)
        self.document_store = document_store
        ds = document_store
        self.serve("/v1/retrieve", ds.RetrieveQuerySchema, ds.retrieve_query)
        self.serve("/v1/statistics", ds.StatisticsQuerySchema, ds.statistics_query)
        self.serve("/v1/inputs", ds.InputsQuerySchema, ds.inputs_query)


class QARestServer(BaseRestServer):
    """reference ``servers.py:140`` — /v1/pw_ai_answer + document listing
    for a question answerer."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **kwargs: Any):
        super().__init__(host, port, **kwargs)
        self.rag = rag_question_answerer
        self.serve(
            "/v1/pw_ai_answer",
            self.rag.AnswerQuerySchema,
            self.rag.answer_query,
        )
        self.serve(
            "/v1/retrieve",
            self.rag.RetrieveQuerySchema,
            self.rag.retrieve,
        )
        self.serve(
            "/v1/statistics",
            self.rag.StatisticsQuerySchema,
            self.rag.statistics,
        )
        self.serve(
            "/v1/pw_list_documents",
            self.rag.InputsQuerySchema,
            self.rag.list_documents,
        )


class QASummaryRestServer(QARestServer):
    """reference ``servers.py:193`` — adds /v1/pw_ai_summary."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **kwargs: Any):
        super().__init__(host, port, rag_question_answerer, **kwargs)
        self.serve(
            "/v1/pw_ai_summary",
            self.rag.SummarizeQuerySchema,
            self.rag.summarize_query,
        )
