"""Document parsers: bytes -> list[(text, metadata)] UDFs
(reference ``xpacks/llm/parsers.py``).

``ParseUtf8`` is the always-available core; the heavyweight parsers
(unstructured / pypdf / vision-LLM) keep the reference API shape and are
gated on their optional packages.
"""

from __future__ import annotations

import re
from typing import Any, Callable

from pathway_tpu.internals.udfs import UDF

__all__ = [
    "ParseUtf8",
    "Utf8Parser",
    "ParseUnstructured",
    "UnstructuredParser",
    "ParseHtml",
    "ParseDocx",
    "PypdfParser",
    "ImageParser",
    "SlideParser",
    "OpenParse",
]


class ParseUtf8(UDF):
    """Decode bytes/str to one UTF-8 text chunk (reference
    ``parsers.py:53``)."""

    def __wrapped__(self, contents: Any, **kwargs: Any) -> list[tuple[str, dict]]:
        if isinstance(contents, bytes):
            text = contents.decode("utf-8", errors="replace")
        else:
            text = str(contents)
        return [(text, {})]


Utf8Parser = ParseUtf8


class _GatedParser(UDF):
    _pkg = ""

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__()
        try:
            __import__(self._pkg)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} requires the optional {self._pkg!r} "
                "package; ParseUtf8 is always available"
            ) from e
        self._args = args
        self._kwargs = kwargs


class ParseUnstructured(UDF):
    """Auto-format document partitioner (reference ``parsers.py:79``).

    Uses the ``unstructured`` package when installed; otherwise falls
    back to the built-in extractors (content-sniffed): PDF via
    ``_pdf.extract_pdf_text``, DOCX and HTML via ``_doc`` (stdlib
    zipfile/xml/html.parser — no dependencies), anything else UTF-8.
    ``mode="single"`` joins everything into one chunk; ``"elements"``
    yields one chunk per block with ``category`` metadata (Title /
    NarrativeText / ListItem / Table, the unstructured vocabulary);
    ``"paged"`` joins per page (PDF) or per document (other formats).
    """

    def __init__(self, mode: str = "single", **kwargs: Any):
        super().__init__()
        if mode not in ("single", "elements", "paged"):
            raise ValueError(f"invalid mode {mode!r}")
        self.mode = mode
        self._kwargs = kwargs

    def _partition_builtin(self, contents: bytes) -> list[tuple[str, dict]]:
        from pathway_tpu.xpacks.llm import _doc

        fmt = _doc.sniff_format(contents)
        if fmt == "pdf":
            from pathway_tpu.xpacks.llm._pdf import extract_pdf_text

            return [
                (t, {"category": "NarrativeText", "page_number": i})
                for i, t in enumerate(extract_pdf_text(contents))
                if t.strip()
            ]
        if fmt == "docx":
            return _doc.extract_docx_blocks(contents)
        if fmt == "html":
            return _doc.extract_html_blocks(contents)
        text = contents.decode("utf-8", errors="replace")
        return [(text, {"category": "NarrativeText"})] if text.strip() else []

    def __wrapped__(self, contents: Any, **kwargs: Any) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            contents = contents.encode()
        try:
            import io

            from unstructured.partition.auto import partition

            elements: list[tuple[str, dict]] = []
            for e in partition(file=io.BytesIO(contents)):
                emeta = getattr(e, "metadata", None)
                meta = {"category": getattr(e, "category", None)}
                page = getattr(emeta, "page_number", None)
                if page is not None:  # paged mode groups by this
                    meta["page_number"] = page
                elements.append((str(e), meta))
        except ImportError:
            elements = self._partition_builtin(contents)
        if self.mode == "elements":
            return elements
        if self.mode == "paged":
            pages: dict[Any, list[str]] = {}
            for text, meta in elements:
                pages.setdefault(meta.get("page_number", 0), []).append(text)
            return [
                ("\n\n".join(parts), {"page_number": pg})
                for pg, parts in sorted(pages.items())
            ]
        return [("\n\n".join(t for t, _ in elements), {})] if elements else []


UnstructuredParser = ParseUnstructured


class ParseHtml(UDF):
    """Built-in HTML parser: block elements with category metadata
    (``_doc.extract_html_blocks``); ``mode="single"`` joins blocks."""

    def __init__(self, mode: str = "single", **kwargs: Any):
        super().__init__()
        if mode not in ("single", "elements"):
            raise ValueError(f"invalid mode {mode!r}")
        self.mode = mode

    def __wrapped__(self, contents: Any, **kwargs: Any) -> list[tuple[str, dict]]:
        from pathway_tpu.xpacks.llm._doc import extract_html_blocks

        blocks = extract_html_blocks(contents)
        if self.mode == "elements":
            return blocks
        return [("\n\n".join(t for t, _ in blocks), {})] if blocks else []


class ParseDocx(UDF):
    """Built-in DOCX parser: WordprocessingML paragraphs/tables with
    category metadata (``_doc.extract_docx_blocks``)."""

    def __init__(self, mode: str = "single", **kwargs: Any):
        super().__init__()
        if mode not in ("single", "elements"):
            raise ValueError(f"invalid mode {mode!r}")
        self.mode = mode

    def __wrapped__(self, contents: bytes, **kwargs: Any) -> list[tuple[str, dict]]:
        from pathway_tpu.xpacks.llm._doc import extract_docx_blocks

        blocks = extract_docx_blocks(contents)
        if self.mode == "elements":
            return blocks
        return [("\n\n".join(t for t, _ in blocks), {})] if blocks else []


class PypdfParser(UDF):
    """PDF-to-text parser (reference ``parsers.py:746``).  Uses ``pypdf``
    when installed; otherwise falls back to the built-in extractor
    (``_pdf.extract_pdf_text``: FlateDecode streams + BT/ET text
    operators), which covers ordinary text PDFs without any dependency."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs: Any):
        super().__init__()
        self.apply_text_cleanup = apply_text_cleanup

    @staticmethod
    def _cleanup(text: str) -> str:
        text = re.sub(r"[ \t]+", " ", text)
        return "\n".join(ln.strip() for ln in text.splitlines()).strip()

    def __wrapped__(self, contents: bytes, **kwargs: Any) -> list[tuple[str, dict]]:
        try:
            from pypdf import PdfReader  # only the import probes: errors
        except ImportError:  # raised INSIDE pypdf later must surface
            PdfReader = None
        if PdfReader is not None:
            import io

            pages = [
                page.extract_text() or ""
                for page in PdfReader(io.BytesIO(contents)).pages
            ]
        else:
            from pathway_tpu.xpacks.llm._pdf import extract_pdf_text

            pages = extract_pdf_text(contents)
        if self.apply_text_cleanup:
            pages = [self._cleanup(p) for p in pages]
        return [(p, {"page": i}) for i, p in enumerate(pages) if p]


class ImageParser(UDF):
    """Vision-LLM image description parser (reference ``parsers.py:396``);
    requires a multimodal ``llm`` chat UDF."""

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str = "Describe the image contents.",
        parse_fn: Callable | None = None,
        **kwargs: Any,
    ):
        super().__init__()
        self.llm = llm
        self.parse_prompt = parse_prompt
        self.parse_fn = parse_fn

    def __wrapped__(self, contents: bytes, **kwargs: Any) -> list[tuple[str, dict]]:
        if self.parse_fn is not None:
            return [(str(self.parse_fn(contents)), {})]
        if self.llm is None:
            raise ValueError("ImageParser needs an llm or a parse_fn")
        import base64

        b64 = base64.b64encode(contents).decode()
        text = self.llm.__wrapped__(
            [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": self.parse_prompt},
                        {
                            "type": "image_url",
                            "image_url": {"url": f"data:image/png;base64,{b64}"},
                        },
                    ],
                }
            ]
        )
        return [(str(text), {})]


class SlideParser(ImageParser):
    """Slide-deck vision parser (reference ``parsers.py:569``,
    license-gated there; here simply ImageParser over rendered pages)."""


class OpenParse(UDF):
    """Layout-aware PDF chunking (reference ``parsers.py:235`` wrapping
    the ``openparse`` package + ``openparse_utils.py``: bbox-positioned
    nodes, heading/table detection, chunk merging).

    Backed by the built-in layout engine in ``_layout.py`` — spans from
    the PDF text matrix, column splitting, font-size heading detection,
    x-aligned-run table detection with ``" | "`` cell separators, and
    bbox-merged chunks where headings open a section and tables are
    never split.  ``table_args={"parsing_algorithm": "llm"}`` (the
    reference's vision-LLM table path) additionally runs ``llm`` over
    each detected table's text to reshape it.

    Args:
        max_chars: chunk budget (a table larger than this still stays
            one chunk — cells are never split).
        table_args: ``{"parsing_algorithm": "native" | "llm"}``;
            "native" (default) emits detected tables as pipe-separated
            rows; "llm" requires ``llm=``.
        llm: chat UDF used when ``parsing_algorithm == "llm"``.
    """

    def __init__(
        self,
        *,
        max_chars: int = 1500,
        table_args: dict | None = None,
        llm: Any = None,
        **kwargs: Any,
    ):
        super().__init__()
        self.max_chars = max_chars
        self.table_args = table_args or {"parsing_algorithm": "native"}
        algorithm = self.table_args.get("parsing_algorithm", "native")
        if algorithm not in ("native", "llm"):
            raise ValueError(
                f"unknown table parsing_algorithm {algorithm!r}"
            )
        if algorithm == "llm" and llm is None:
            raise ValueError("parsing_algorithm='llm' requires llm=...")
        self.llm = llm

    def __wrapped__(self, contents: bytes, **kwargs: Any) -> list[tuple[str, dict]]:
        from pathway_tpu.xpacks.llm._layout import chunk_pdf_layout

        chunks = chunk_pdf_layout(contents, max_chars=self.max_chars)
        if self.table_args.get("parsing_algorithm") == "llm":
            # rewrite ONLY each detected table's rows in place — the
            # surrounding prose of a mixed chunk must pass through
            # untouched
            out = []
            for text, meta in chunks:
                for table_text in meta.get("tables", ()):
                    rewritten = str(
                        self.llm.__wrapped__(
                            [
                                {
                                    "role": "user",
                                    "content": (
                                        "Rewrite this extracted table as "
                                        "clean markdown, preserving every "
                                        "cell:\n" + table_text
                                    ),
                                }
                            ]
                        )
                    )
                    text = text.replace(table_text, rewritten)
                out.append((text, meta))
            return out
        return chunks
