"""``pw.xpacks.llm`` — the live RAG stack (reference
``python/pathway/xpacks/llm/``): embedders, llms, parsers, splitters,
rerankers, DocumentStore, VectorStore, question answering, servers,
prompts.  TPU-native where the reference uses torch."""

from pathway_tpu.xpacks.llm._typing import Doc, DocTransformer, DocTransformerCallable
from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    splitters,
)
from pathway_tpu.xpacks.llm import document_store, question_answering, servers, vector_store

__all__ = [
    "Doc",
    "DocTransformer",
    "DocTransformerCallable",
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
    "document_store",
    "question_answering",
    "servers",
    "vector_store",
]
