"""Embedders — text -> vector UDFs (reference ``xpacks/llm/embedders.py``).

TPU re-design: :class:`TPUEncoderEmbedder` (and its reference-named alias
:class:`SentenceTransformerEmbedder`, reference ``embedders.py:270-327``
which runs per-row torch ``model.encode``) runs a flax encoder jitted in
bf16, **one batched call per engine epoch** (``BatchUDF`` contract), with
tensor/data-parallel sharding when given a mesh.

API-based embedders (OpenAI/LiteLLM/Gemini, reference ``:85/:180/:330``)
keep the reference's async-UDF shape (capacity/retry/cache composition)
and are gated on their client packages — this environment has no network
egress.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.internals import udfs
from pathway_tpu.internals.udfs import UDF

__all__ = [
    "BaseEmbedder",
    "TPUEncoderEmbedder",
    "SentenceTransformerEmbedder",
    "OpenAIEmbedder",
    "LiteLLMEmbedder",
    "GeminiEmbedder",
]

_PRESETS = {
    "all-minilm-l6-v2": "MINILM_L6",
    "sentence-transformers/all-minilm-l6-v2": "MINILM_L6",
    "baai/bge-small-en-v1.5": "BGE_SMALL",
    "bge-small": "BGE_SMALL",
    "baai/bge-base-en-v1.5": "BGE_BASE",
    "bge-base": "BGE_BASE",
    "baai/bge-large-en-v1.5": "BGE_LARGE",
    "bge-large": "BGE_LARGE",
    "intfloat/e5-base-v2": "E5_BASE",
    "e5-base": "E5_BASE",
}


def _resolve_config(model: str):
    from pathway_tpu.models import encoder as enc

    name = _PRESETS.get(model.lower())
    if name is None:
        name = "MINILM_L6"
    return getattr(enc, name)


class BaseEmbedder(UDF):
    def get_embedding_dimension(self, **kwargs: Any) -> int:
        """Probe: embed a short string, report its width (reference
        ``BaseEmbedder.get_embedding_dimension``)."""
        out = self._embed_batch(["."])[0]
        return int(np.asarray(out).reshape(-1).shape[0])

    def _embed_batch(self, texts: list[str]) -> list:
        raise NotImplementedError


class TPUEncoderEmbedder(BaseEmbedder):
    """Flax sentence encoder on TPU; one jitted call per epoch.

    ``model`` picks an architecture preset (MiniLM/BGE/E5 family); random
    deterministic weights unless ``params`` (a flax pytree) is passed or a
    local HF tokenizer/weights cache exists.
    """

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        mesh: Any = None,
        max_batch_size: int | None = 1024,
        call_kwargs: dict | None = None,
        params: Any = None,
        config: Any = None,
        sequence_axis: str | None = None,
        **kwargs: Any,
    ):
        super().__init__(max_batch_size=max_batch_size, **kwargs)
        import os

        from pathway_tpu.parallel import JittedEncoder

        # a local directory means a real HF checkpoint (weights + vocab);
        # otherwise an architecture preset with deterministic random init.
        # With a checkpoint, config.json decides pooling etc. unless the
        # caller explicitly passed a config.
        checkpoint_dir = model if os.path.isdir(model) else None
        if config is None:
            cfg = None if checkpoint_dir else _resolve_config(model)
        else:
            cfg = config
        self.model = model
        self.encoder = JittedEncoder(
            cfg, mesh=mesh, model_name=model, params=params,
            max_batch=max_batch_size or 1024, checkpoint_dir=checkpoint_dir,
            sequence_axis=sequence_axis,
        )

    def _embed_batch(self, texts: list[str]) -> list:
        emb = self.encoder.encode([t if t else "." for t in texts])
        return [row for row in emb]

    def __batch__(self, texts: list[str]) -> list:
        return self._embed_batch([str(t) for t in texts])

    def __wrapped__(self, text: str) -> Any:
        return self._embed_batch([str(text)])[0]


#: reference-compatible name — in the reference this wraps torch
#: SentenceTransformers (``embedders.py:270``); here it is the TPU encoder
SentenceTransformerEmbedder = TPUEncoderEmbedder


class _ApiEmbedder(BaseEmbedder):
    """Shared shape of the network API embedders."""

    _client_pkg = ""

    def __init__(
        self,
        *,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        **call_kwargs: Any,
    ):
        executor = udfs.async_executor(
            capacity=capacity, retry_strategy=retry_strategy
        )
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.model = model
        self.call_kwargs = call_kwargs
        try:
            __import__(self._client_pkg)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} needs the {self._client_pkg!r} package "
                "(and network access); use TPUEncoderEmbedder for local "
                "TPU embedding"
            ) from e

    def _embed_batch(self, texts: list[str]) -> list:
        import asyncio

        async def run_all() -> list:
            return await asyncio.gather(*[self.__wrapped__(t) for t in texts])

        return asyncio.run(run_all())


class OpenAIEmbedder(_ApiEmbedder):
    """reference ``embedders.py:85``"""

    _client_pkg = "openai"

    async def __wrapped__(self, input: str, **kwargs: Any) -> Any:
        import openai

        client = openai.AsyncOpenAI()
        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        ret = await client.embeddings.create(input=[input or "."], **kw)
        return np.asarray(ret.data[0].embedding)


class LiteLLMEmbedder(_ApiEmbedder):
    """reference ``embedders.py:180``"""

    _client_pkg = "litellm"

    async def __wrapped__(self, input: str, **kwargs: Any) -> Any:
        import litellm

        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        ret = await litellm.aembedding(input=[input or "."], **kw)
        return np.asarray(ret.data[0]["embedding"])


class GeminiEmbedder(_ApiEmbedder):
    """reference ``embedders.py:330``"""

    _client_pkg = "google.generativeai"

    async def __wrapped__(self, input: str, **kwargs: Any) -> Any:
        import google.generativeai as genai

        kw = {**self.call_kwargs, **kwargs}
        if self.model is not None:
            kw.setdefault("model", self.model)
        ret = genai.embed_content(content=input or ".", **kw)
        return np.asarray(ret["embedding"])
