"""Built-in PDF text extraction (no third-party dependency).

Fallback engine for :class:`~pathway_tpu.xpacks.llm.parsers.PypdfParser`
(reference ``parsers.py:746`` wraps the ``pypdf`` package; this module
implements the subset that covers ordinary text PDFs natively):

- content streams located via ``stream``/``endstream`` framing,
- FlateDecode (zlib) decompression — the compression used by virtually
  every text PDF,
- text extraction from ``BT``/``ET`` blocks: ``Tj``, ``'``, ``"`` and
  ``TJ`` show operators, literal ``(...)`` strings with escape handling,
  and hex ``<...>`` strings,
- ``Td``/``TD``/``T*``/``Tm`` line-advance heuristics for newlines.

Complex encodings (CID/Type0 fonts with ToUnicode CMaps) are out of
scope: those documents need the real ``pypdf`` (used automatically when
installed).
"""

from __future__ import annotations

import re
import zlib

_STREAM = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.DOTALL)
_BT_ET = re.compile(rb"BT(.*?)ET", re.DOTALL)
#: literal string body: escapes, plain chars, or ONE level of balanced
#: unescaped parentheses (legal per the PDF spec; deeper nesting is rare)
_LIT = rb"(?:\\.|[^\\()]|\((?:\\.|[^\\()])*\))*"
#: TJ array body: literal strings, hex strings, or non-bracket chars —
#: so a ']' inside a string does not end the array early
_ARR = rb"(?:\(" + _LIT + rb"\)|<[0-9A-Fa-f\s]*>|[^\]()<>])*"
#: one text-showing or line-moving operator inside a BT block
_TEXT_OP = re.compile(
    rb"\((?P<lit>" + _LIT + rb")\)\s*(?P<op>Tj|'|\")"  # (s) Tj / ' / "
    rb"|\[(?P<arr>" + _ARR + rb")\]\s*TJ"  # [(a) -250 (b)] TJ
    rb"|<(?P<hex>[0-9A-Fa-f\s]*)>\s*(?P<hop>Tj|'|\")"
    rb"|(?P<nl>T\*|Td|TD|Tm)",
    re.DOTALL,
)
_ARR_STR = re.compile(
    rb"\((?P<lit>" + _LIT + rb")\)|<(?P<hex>[0-9A-Fa-f\s]*)>"
)

_ESCAPES = {
    b"n": b"\n",
    b"r": b"\r",
    b"t": b"\t",
    b"b": b"\b",
    b"f": b"\f",
    b"(": b"(",
    b")": b")",
    b"\\": b"\\",
}


def _unescape(raw: bytes) -> str:
    out = bytearray()
    i = 0
    n = len(raw)
    while i < n:
        c = raw[i : i + 1]
        if c == b"\\" and i + 1 < n:
            nxt = raw[i + 1 : i + 2]
            if nxt in _ESCAPES:
                out += _ESCAPES[nxt]
                i += 2
                continue
            if b"0" <= nxt <= b"7":  # \ddd octal (1-3 digits, 0-7 only)
                j = i + 1
                while j < min(i + 4, n) and b"0" <= raw[j : j + 1] <= b"7":
                    j += 1
                out.append(int(raw[i + 1 : j], 8) & 0xFF)
                i = j
                continue
            i += 1  # line continuation / unknown escape: drop the backslash
            continue
        out += c
        i += 1
    return out.decode("latin-1")


def _hex_text(h: bytes) -> str:
    h = re.sub(rb"\s", b"", h)
    if len(h) % 2:
        h += b"0"
    data = bytes.fromhex(h.decode("ascii"))
    if len(data) >= 2 and all(b == 0 for b in data[::2]):
        # UTF-16BE-looking two-byte codes (common Identity-H simple case)
        return data.decode("utf-16-be", errors="ignore")
    return data.decode("latin-1")


def _block_text(block: bytes) -> str:
    parts: list[str] = []
    for m in _TEXT_OP.finditer(block):
        if m.group("nl") is not None:
            if parts and not parts[-1].endswith("\n"):
                parts.append("\n")
            continue
        if m.group("lit") is not None:
            parts.append(_unescape(m.group("lit")))
            if m.group("op") in (b"'", b'"'):
                parts.append("\n")
        elif m.group("arr") is not None:
            for s in _ARR_STR.finditer(m.group("arr")):
                if s.group("lit") is not None:
                    parts.append(_unescape(s.group("lit")))
                else:
                    parts.append(_hex_text(s.group("hex")))
        elif m.group("hex") is not None:
            parts.append(_hex_text(m.group("hex")))
            if m.group("hop") in (b"'", b'"'):
                parts.append("\n")
    return "".join(parts)


def extract_pdf_text(data: bytes) -> list[str]:
    """Text of each content stream that contains text operators, in file
    order (approximates page order for ordinary single-stream pages)."""
    if not data.lstrip().startswith(b"%PDF"):
        raise ValueError("not a PDF document (missing %PDF header)")
    pages: list[str] = []
    for m in _STREAM.finditer(data):
        raw = m.group(1)
        try:
            content = zlib.decompress(raw)
        except zlib.error:
            content = raw
        blocks = _BT_ET.findall(content)
        if not blocks:
            continue
        text = "\n".join(filter(None, (_block_text(b).strip() for b in blocks)))
        if text:
            pages.append(text)
    return pages
