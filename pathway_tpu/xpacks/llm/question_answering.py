"""Question answering over a DocumentStore
(reference ``xpacks/llm/question_answering.py``).

``BaseRAGQuestionAnswerer`` (reference ``:314``): retrieve -> prompt ->
LLM, served over REST.  ``AdaptiveRAGQuestionAnswerer`` (reference
``:620``) implements the geometric document-count escalation of
``answer_with_geometric_rag_strategy`` (``:97``): start with a few docs,
re-ask with geometrically more until the LLM finds an answer.

TPU redesign note: the adaptive loop retrieves the maximum needed docs
ONCE as-of-now (one sharded matmul) and escalates over prefixes — same
ranking and same LLM call sequence as the reference's repeated
re-retrievals, minus the extra index round-trips.
"""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.llms import prompt_chat_single_qa
from pathway_tpu.xpacks.llm.servers import QARestServer, QASummaryRestServer

__all__ = [
    "BaseQuestionAnswerer",
    "SummaryQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "AdaptiveRAGQuestionAnswerer",
    "answer_with_geometric_rag_strategy",
    "answer_with_geometric_rag_strategy_from_index",
    "DeckRetriever",
]


class BaseQuestionAnswerer:
    """Protocol: table-in/table-out query surfaces (reference ``:288``)."""

    AnswerQuerySchema: type = pw.Schema
    RetrieveQuerySchema: type = pw.Schema
    StatisticsQuerySchema: type = pw.Schema
    InputsQuerySchema: type = pw.Schema

    def answer_query(self, pw_ai_queries: Table) -> Table:
        raise NotImplementedError

    def retrieve(self, retrieval_queries: Table) -> Table:
        raise NotImplementedError

    def statistics(self, info_queries: Table) -> Table:
        raise NotImplementedError

    def list_documents(self, info_queries: Table) -> Table:
        raise NotImplementedError


class SummaryQuestionAnswerer(BaseQuestionAnswerer):
    """adds summarize_query (reference ``:311``)."""

    SummarizeQuerySchema: type = pw.Schema

    def summarize_query(self, summarize_queries: Table) -> Table:
        raise NotImplementedError


def _call_llm(llm: Any, messages: list[dict]) -> str:
    """Invoke a chat UDF host-side (inside another UDF's body)."""
    import inspect

    fun = llm.__wrapped__ if hasattr(llm, "__wrapped__") else llm
    out = fun(messages)
    if inspect.isawaitable(out):
        import asyncio

        out = asyncio.run(out)
    return "" if out is None else str(out)


class BaseRAGQuestionAnswerer(SummaryQuestionAnswerer):
    """reference ``question_answering.py:314``"""

    def __init__(
        self,
        llm: Any,
        indexer: DocumentStore,
        *,
        prompt_template: Callable[[str, list], str] | None = None,
        summarize_template: Any = None,
        search_topk: int = 6,
    ):
        self.llm = llm
        self.indexer = indexer
        self.prompt_template = prompt_template or prompts.prompt_qa_geometric_rag
        self.summarize_template = summarize_template
        self.search_topk = search_topk
        self.server: QARestServer | None = None

    # -- REST schemas (reference :379-448) ------------------------------
    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None = pw.column_definition(default_value=None)
        model: str | None = pw.column_definition(default_value=None)
        return_context_docs: bool | None = pw.column_definition(default_value=False)
        # multi-tenant serving: names the tenant for admission control /
        # SLO-class scheduling; absent → "default" tenant
        tenant: str | None = pw.column_definition(default_value=None)

    class RetrieveQuerySchema(DocumentStore.RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(DocumentStore.InputsQuerySchema):
        pass

    class SummarizeQuerySchema(pw.Schema):
        text_list: Any

    # -- query surfaces -------------------------------------------------
    def answer_query(self, pw_ai_queries: Table) -> Table:
        """reference ``:451``"""
        as_retrieval = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=pw.apply(lambda _p: self.search_topk, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=pw.apply(lambda _p: None, pw_ai_queries.prompt),
        )
        with_docs = self.indexer.retrieve_query(as_retrieval)
        combined = pw_ai_queries.with_columns(docs=with_docs.result)

        template = self.prompt_template

        def answer(prompt: str, docs: list, return_context: Any) -> dict:
            docs = list(docs or ())
            text = template(prompt, docs)
            response = _call_llm(self.llm, prompt_chat_single_qa(text))
            out: dict = {"response": response}
            if return_context:
                out["context_docs"] = docs
            return out

        return combined.select(
            result=pw.apply(
                answer, combined.prompt, combined.docs, combined.return_context_docs
            )
        )

    def retrieve(self, retrieval_queries: Table) -> Table:
        return self.indexer.retrieve_query(retrieval_queries)

    def statistics(self, info_queries: Table) -> Table:
        return self.indexer.statistics_query(info_queries)

    def list_documents(self, info_queries: Table) -> Table:
        return self.indexer.inputs_query(info_queries)

    def summarize_query(self, summarize_queries: Table) -> Table:
        """reference ``:500``"""

        def summarize(text_list: Any) -> str:
            texts = list(text_list or ())
            prompt = (
                self.summarize_template(texts)
                if callable(self.summarize_template)
                else f"Summarize the following:\n\n" + "\n".join(map(str, texts))
            )
            return _call_llm(self.llm, prompt_chat_single_qa(prompt))

        return summarize_queries.select(
            result=pw.apply(summarize, summarize_queries.text_list)
        )

    # -- serving --------------------------------------------------------
    def build_server(self, host: str, port: int, **kwargs: Any) -> QASummaryRestServer:
        """reference ``:527``"""
        self.server = QASummaryRestServer(host, port, self, **kwargs)
        return self.server

    def run_server(self, host: str = "0.0.0.0", port: int = 8000, threaded: bool = False, **kwargs: Any):
        """reference ``:600``"""
        if self.server is None:
            self.build_server(host, port)
        return self.server.run(threaded=threaded, **kwargs)


# ---------------------------------------------------------------------------
# Adaptive RAG (reference :97-285, :620)


def answer_with_geometric_rag_strategy(
    questions: list[str],
    documents: list[list[str]],
    llm: Any,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    strict_prompt: bool = False,
) -> list[str]:
    """Host-side geometric escalation (reference ``:97``): ask with n docs;
    if the answer is "No information found." retry with n*factor docs."""
    answers = []
    for q, docs in zip(questions, documents):
        n = n_starting_documents
        answer = prompts.NO_INFO
        for _ in range(max_iterations):
            subset = docs[:n]
            text = prompts.prompt_qa_geometric_rag(q, subset)
            answer = _call_llm(llm, prompt_chat_single_qa(text))
            if answer.strip() and prompts.NO_INFO.lower() not in answer.lower():
                break
            if n >= len(docs):
                break
            n *= factor
        answers.append(answer)
    return answers


def answer_with_geometric_rag_strategy_from_index(
    questions: Table,
    index: Any,
    documents_column: Any,
    llm: Any,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    metadata_filter: Any = None,
    strict_prompt: bool = False,
) -> Table:
    """reference ``:162`` — retrieval + geometric answering as a Table op.
    ``documents_column`` names the column of the INDEXED table holding the
    document text (reference semantics); the questions table must have a
    ``query`` column.  Retrieves max-needed docs once as-of-now, escalates
    over prefixes."""
    k_max = n_starting_documents * (factor ** (max_iterations - 1))
    doc_col = (
        documents_column._name
        if hasattr(documents_column, "_name")
        else str(documents_column)
    )
    query_col = questions.query
    replies = index.query_as_of_now(
        query_col, number_of_matches=k_max, metadata_filter=metadata_filter
    )

    def run_strategy(question: str, datas: tuple) -> str:
        docs = [
            str((d or {}).get(doc_col, "")) if isinstance(d, dict) else str(d)
            for d in (datas or ())
        ]
        return answer_with_geometric_rag_strategy(
            [question], [docs], llm, n_starting_documents, factor, max_iterations,
            strict_prompt,
        )[0]

    return replies.select(
        *[replies[c] for c in questions.column_names() if c in replies.column_names()],
        result=pw.apply(run_strategy, query_col, replies["_pw_index_reply"]),
    )


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """reference ``question_answering.py:620``"""

    def __init__(
        self,
        llm: Any,
        indexer: DocumentStore,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs: Any,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, pw_ai_queries: Table) -> Table:
        k_max = self.n_starting_documents * (
            self.factor ** (self.max_iterations - 1)
        )
        as_retrieval = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=pw.apply(lambda _p: k_max, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=pw.apply(lambda _p: None, pw_ai_queries.prompt),
        )
        with_docs = self.indexer.retrieve_query(as_retrieval)
        combined = pw_ai_queries.with_columns(docs=with_docs.result)

        def answer(prompt: str, docs: list) -> dict:
            texts = [d.get("text", "") for d in (docs or ())]
            response = answer_with_geometric_rag_strategy(
                [prompt], [texts], self.llm, self.n_starting_documents,
                self.factor, self.max_iterations, self.strict_prompt,
            )[0]
            return {"response": response}

        return combined.select(
            result=pw.apply(answer, combined.prompt, combined.docs)
        )


class DeckRetriever(BaseRAGQuestionAnswerer):
    """Slide-deck retrieval app (reference ``:736``): answer = the matched
    slides themselves."""

    def answer_query(self, pw_ai_queries: Table) -> Table:
        as_retrieval = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=pw.apply(lambda _p: self.search_topk, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=pw.apply(lambda _p: None, pw_ai_queries.prompt),
        )
        with_docs = self.indexer.retrieve_query(as_retrieval)
        return with_docs.select(result=with_docs.result)
