"""Layout-aware PDF chunking (reference
``python/pathway/xpacks/llm/openparse_utils.py`` + the ``openparse``
package it wraps: bbox-positioned text nodes, heading detection, table
detection, and chunk merging).

The reference delegates layout analysis to pymupdf/openparse; this
module derives the same structure from the PDF content streams directly
(no third-party dependency), on top of the tokenizer in ``_pdf.py``:

- **spans**: every shown string with its (x, y) from the text matrix
  (``Tm``/``Td``/``TD``/``T*``) and font size (``Tf`` scaled by ``Tm``),
- **lines**: spans grouped by baseline y, sorted by x,
- **columns**: lines clustered by x-extent gaps, read column-major
  (left column top-to-bottom, then the next) — multi-column PDFs come
  out in reading order instead of interleaved,
- **headings**: lines whose font size clears the body median by >=15%,
- **tables**: >=2 consecutive lines whose >=2 span x-positions align
  within a tolerance — emitted as one node with ``" | "`` cell
  separators, never split across chunks,
- **chunks**: nodes merged in reading order up to a character budget;
  headings start a new chunk and prefix their section's text.
"""

from __future__ import annotations

import re
import zlib
from dataclasses import dataclass, field
from typing import Any

from pathway_tpu.xpacks.llm._pdf import (
    _ARR_STR,
    _BT_ET,
    _LIT,
    _STREAM,
    _hex_text,
    _unescape,
)

_NUM = rb"[-+]?(?:\d+\.?\d*|\.\d+)"
#: positioned-text tokenizer: operands captured with their operators
_TOK = re.compile(
    rb"(?P<tm>(?:" + _NUM + rb"\s+){6})Tm"
    rb"|(?P<td>(?:" + _NUM + rb"\s+){2})(?P<tdop>Td|TD)"
    rb"|(?P<tl>" + _NUM + rb")\s+TL"
    rb"|/(?P<font>\S+)\s+(?P<fsize>" + _NUM + rb")\s+Tf"
    rb"|(?P<tstar>T\*)"
    rb"|\((?P<lit>" + _LIT + rb")\)\s*(?P<lop>Tj|'|\")"
    rb"|\[(?P<arr>(?:\(" + _LIT + rb"\)|<[0-9A-Fa-f\s]*>|[^\]()<>])*)\]\s*TJ"
    rb"|<(?P<hex>[0-9A-Fa-f\s]*)>\s*(?P<hop>Tj|'|\")",
    re.DOTALL,
)


@dataclass
class PdfSpan:
    """One shown string and where it was shown."""

    x: float
    y: float
    size: float
    text: str


@dataclass
class LayoutNode:
    """A structural unit: heading, paragraph-ish text block, or table
    (openparse ``Node`` counterpart with ``bbox``/``variant``)."""

    kind: str  # "heading" | "text" | "table"
    text: str
    page: int
    bbox: tuple[float, float, float, float]  # x0, y0, x1, y1


def extract_pdf_spans(data: bytes) -> list[list[PdfSpan]]:
    """Positioned spans per page (content streams with text, in file
    order, like :func:`_pdf.extract_pdf_text`)."""
    if not data.lstrip().startswith(b"%PDF"):
        raise ValueError("not a PDF document (missing %PDF header)")
    pages: list[list[PdfSpan]] = []
    for m in _STREAM.finditer(data):
        raw = m.group(1)
        try:
            content = zlib.decompress(raw)
        except zlib.error:
            content = raw
        spans: list[PdfSpan] = []
        for block in _BT_ET.findall(content):
            spans.extend(_block_spans(block))
        if spans:
            pages.append(spans)
    return pages


def _block_spans(block: bytes) -> list[PdfSpan]:
    # text state per BT block (PDF 32000-1:2008 §9.4)
    x = y = 0.0
    lx = ly = 0.0  # line matrix origin (Td moves relative to it)
    size = 12.0
    scale = 1.0  # vertical scale from Tm's d component
    leading = 14.0
    out: list[PdfSpan] = []

    def show(text: str) -> None:
        if text:
            out.append(PdfSpan(x, y, size * scale, text))

    for m in _TOK.finditer(block):
        if m.group("tm") is not None:
            a, b, c, d, e, f = (float(v) for v in m.group("tm").split())
            lx = x = e
            ly = y = f
            scale = abs(d) or 1.0
        elif m.group("td") is not None:
            tx, ty = (float(v) for v in m.group("td").split())
            if m.group("tdop") == b"TD":
                leading = -ty if ty else leading
            lx = x = lx + tx
            ly = y = ly + ty
        elif m.group("tl") is not None:
            leading = float(m.group("tl"))
        elif m.group("fsize") is not None:
            size = float(m.group("fsize"))
        elif m.group("tstar") is not None:
            ly = y = ly - leading
            x = lx
        elif m.group("lit") is not None:
            if m.group("lop") in (b"'", b'"'):
                # ' and " move to the next line FIRST, then show
                # (ISO 32000-1 §9.4.3)
                ly = y = ly - leading
                x = lx
            show(_unescape(m.group("lit")))
        elif m.group("arr") is not None:
            parts = []
            for s in _ARR_STR.finditer(m.group("arr")):
                if s.group("lit") is not None:
                    parts.append(_unescape(s.group("lit")))
                else:
                    parts.append(_hex_text(s.group("hex")))
            show("".join(parts))
        elif m.group("hex") is not None:
            if m.group("hop") in (b"'", b'"'):
                ly = y = ly - leading
                x = lx
            show(_hex_text(m.group("hex")))
    return out


@dataclass
class _Line:
    y: float
    size: float
    spans: list[PdfSpan] = field(default_factory=list)

    @property
    def x0(self) -> float:
        return min(s.x for s in self.spans)

    @property
    def x1(self) -> float:
        # span width estimate: ~0.5em per char (no font metrics without
        # the font program; adequate for column/table geometry)
        last = max(self.spans, key=lambda s: s.x)
        return last.x + 0.5 * last.size * len(last.text)

    @property
    def text(self) -> str:
        return " ".join(
            s.text.strip() for s in sorted(self.spans, key=lambda s: s.x)
        ).strip()


def _group_lines(spans: list[PdfSpan]) -> list[_Line]:
    lines: list[_Line] = []
    for s in sorted(spans, key=lambda s: (-s.y, s.x)):
        for line in lines:
            if abs(line.y - s.y) <= max(2.0, 0.4 * max(line.size, s.size)):
                line.spans.append(s)
                line.size = max(line.size, s.size)
                break
        else:
            lines.append(_Line(y=s.y, size=s.size, spans=[s]))
    lines.sort(key=lambda ln: -ln.y)
    return lines


def _span_x1(s: PdfSpan) -> float:
    # ~0.5em per char (no font metrics without the font program;
    # adequate for column/table geometry)
    return s.x + 0.5 * s.size * len(s.text)


def _split_columns(spans: list[PdfSpan]) -> list[list[PdfSpan]]:
    """Cluster SPANS into columns before any line grouping — two columns
    share baselines, so grouping lines page-wide would weld them into
    one interleaved line.  A vertical gutter (almost no span crosses it)
    splits the page; reading order is the left column first.  A
    full-width title stays with the left/reading-first column."""
    if len(spans) < 6:
        return [spans]
    starts = sorted({s.x for s in spans})
    best_gap, split_at = 0.0, None
    for a, b in zip(starts, starts[1:]):
        if b - a > best_gap:
            best_gap, split_at = b - a, (a + b) / 2.0
    page_w = max(_span_x1(s) for s in spans) - min(s.x for s in spans)
    if split_at is None or best_gap < 0.25 * max(page_w, 1.0):
        return [spans]
    left = [s for s in spans if s.x < split_at]
    right = [s for s in spans if s.x >= split_at]
    crossers = sum(1 for s in left if _span_x1(s) > split_at + 0.1 * page_w)
    if not left or not right or crossers > max(1, len(left) // 4):
        return [spans]
    return [left, right]


def _detect_tables(lines: list[_Line]) -> list[tuple[int, int]]:
    """(start, end) line-index ranges forming tables: runs of >=2 lines
    with >=2 cells whose x positions align within a tolerance."""
    def cell_xs(line: _Line) -> list[float]:
        return sorted(s.x for s in line.spans)

    ranges: list[tuple[int, int]] = []
    i = 0
    while i < len(lines):
        xs = cell_xs(lines[i])
        if len(xs) < 2:
            i += 1
            continue
        j = i + 1
        while j < len(lines):
            xs2 = cell_xs(lines[j])
            if len(xs2) != len(xs):
                break
            tol = max(3.0, 0.5 * lines[j].size)
            if any(abs(a - b) > tol for a, b in zip(xs, xs2)):
                break
            j += 1
        if j - i >= 2:
            ranges.append((i, j))
            i = j
        else:
            i += 1
    return ranges


def pdf_layout_nodes(data: bytes) -> list[LayoutNode]:
    """Structural nodes in reading order across all pages."""
    nodes: list[LayoutNode] = []
    for page_no, spans in enumerate(extract_pdf_spans(data)):
        sizes = sorted(s.size for s in spans)
        median = sizes[len(sizes) // 2] if sizes else 12.0
        for col_spans in _split_columns(spans):
            column = _group_lines(col_spans)
            tables = _detect_tables(column)
            i = 0
            while i < len(column):
                t = next((t for t in tables if t[0] == i), None)
                if t is not None:
                    rows = column[t[0] : t[1]]
                    text = "\n".join(
                        " | ".join(
                            s.text.strip()
                            for s in sorted(r.spans, key=lambda s: s.x)
                        )
                        for r in rows
                    )
                    nodes.append(
                        LayoutNode(
                            "table",
                            text,
                            page_no,
                            _bbox(rows),
                        )
                    )
                    i = t[1]
                    continue
                line = column[i]
                kind = (
                    "heading"
                    if line.size >= 1.15 * median and line.text
                    else "text"
                )
                if line.text:
                    nodes.append(
                        LayoutNode(kind, line.text, page_no, _bbox([line]))
                    )
                i += 1
    return _merge_text_runs(nodes)


def _bbox(lines: list[_Line]) -> tuple[float, float, float, float]:
    return (
        min(ln.x0 for ln in lines),
        min(ln.y - ln.size for ln in lines),
        max(ln.x1 for ln in lines),
        max(ln.y for ln in lines),
    )


def _merge_text_runs(nodes: list[LayoutNode]) -> list[LayoutNode]:
    """Adjacent text lines on the same page merge into paragraphs-ish
    blocks; headings and tables stay their own nodes."""
    out: list[LayoutNode] = []
    for node in nodes:
        if (
            node.kind == "text"
            and out
            and out[-1].kind == "text"
            and out[-1].page == node.page
        ):
            prev = out[-1]
            out[-1] = LayoutNode(
                "text",
                prev.text + "\n" + node.text,
                node.page,
                (
                    min(prev.bbox[0], node.bbox[0]),
                    min(prev.bbox[1], node.bbox[1]),
                    max(prev.bbox[2], node.bbox[2]),
                    max(prev.bbox[3], node.bbox[3]),
                ),
            )
        else:
            out.append(node)
    return out


def chunk_pdf_layout(
    data: bytes, *, max_chars: int = 1500
) -> list[tuple[str, dict[str, Any]]]:
    """Layout-aware chunks: ``(text, metadata)`` pairs where metadata
    carries page, merged bbox, node kinds, and the governing heading.
    Headings open a new chunk; tables are never split (an oversized
    table is its own chunk, cells intact)."""
    nodes = pdf_layout_nodes(data)
    chunks: list[tuple[str, dict[str, Any]]] = []
    cur: list[LayoutNode] = []
    cur_heading: str | None = None

    def flush() -> None:
        nonlocal cur
        if not cur:
            return
        text = "\n".join(n.text for n in cur)
        meta = {
            "page": cur[0].page,
            "bbox": [
                min(n.bbox[0] for n in cur),
                min(n.bbox[1] for n in cur),
                max(n.bbox[2] for n in cur),
                max(n.bbox[3] for n in cur),
            ],
            "kinds": [n.kind for n in cur],
            "heading": cur_heading,
            "tables": [n.text for n in cur if n.kind == "table"],
        }
        chunks.append((text, meta))
        cur = []

    size = 0
    for node in nodes:
        if node.kind == "heading":
            flush()
            cur_heading = node.text
            cur = [node]
            size = len(node.text)
            continue
        if size + len(node.text) > max_chars and cur:
            flush()
            size = 0
        cur.append(node)
        size += len(node.text)
        if node.kind == "table" and size > max_chars:
            flush()  # oversized table: own chunk, never split
            size = 0
    flush()
    return chunks
