"""DocumentStore: live parse -> post-process -> split -> index pipeline
(reference ``xpacks/llm/document_store.py:233-471``).

Input tables come from connectors with columns ``data`` (bytes|str) and
optionally ``_metadata`` (dict).  The store builds the chunk table, feeds
the retriever's :class:`~pathway_tpu.stdlib.indexing.DataIndex` (TPU
sharded KNN / BM25 / hybrid), and answers retrieve / statistics / inputs
queries with as-of-now consistency.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.udfs import UDF
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndexFactory

__all__ = ["DocumentStore", "SlidesDocumentStore"]


def _merge_filters(metadata_filter: str | None, globpattern: str | None):
    """Combine a metadata filter with a path glob (reference
    ``merge_filters``, ``document_store.py:356``).  Returns a CALLABLE
    (metadata -> bool) so glob patterns never pass through string
    interpolation (no quoting/injection issues); a malformed filter
    fails CLOSED (rejects everything) rather than disabling filtering."""
    import fnmatch

    if not metadata_filter and not globpattern:
        return None
    meta_fn = None
    if metadata_filter:
        from pathway_tpu.stdlib.indexing.filters import compile_filter

        try:
            meta_fn = compile_filter(metadata_filter)
        except Exception:
            return lambda m: False  # fail closed on malformed filters

    def run(meta: dict | None) -> bool:
        m = meta or {}
        if meta_fn is not None and not meta_fn(m):
            return False
        if globpattern and not fnmatch.fnmatch(str(m.get("path", "")), globpattern):
            return False
        return True

    return run


class DocumentStore:
    """reference ``document_store.py:233``"""

    def __init__(
        self,
        docs: Table | Iterable[Table],
        retriever_factory: InnerIndexFactory,
        parser: UDF | Callable | None = None,
        splitter: UDF | Callable | None = None,
        doc_post_processors: list[Callable] | None = None,
    ):
        from pathway_tpu.xpacks.llm.parsers import ParseUtf8
        from pathway_tpu.xpacks.llm.splitters import null_splitter

        self.docs = list(docs) if not isinstance(docs, Table) else [docs]
        self.retriever_factory = retriever_factory
        self.parser = parser if parser is not None else ParseUtf8()
        self.splitter = splitter if splitter is not None else null_splitter
        self.doc_post_processors = doc_post_processors or []
        self._index: DataIndex | None = None
        self._input_table: Table | None = None
        self._chunks: Table | None = None
        self.build_pipeline()

    # ------------------------------------------------------------------
    @staticmethod
    def _as_transformer_expr(fn: Any, *args: Any) -> Any:
        """UDFs are called directly (batched when they define __batch__);
        bare callables go through pw.apply."""
        if isinstance(fn, UDF):
            return fn(*args)
        return pw.apply(fn, *args)

    def build_pipeline(self) -> None:
        """reference ``document_store.py:286``"""
        tables = []
        for t in self.docs:
            cols: dict[str, Any] = {"data": t.data}
            if "_metadata" in t.column_names():
                cols["_metadata"] = t["_metadata"]
            else:
                cols["_metadata"] = pw.apply(lambda d: {}, t.data)
            tables.append(t.select(**cols))
        input_table = tables[0] if len(tables) == 1 else tables[0].concat_reindex(*tables[1:])
        self._input_table = input_table

        parsed = input_table.with_columns(
            _parsed=self._as_transformer_expr(self.parser, input_table.data)
        )
        # one row per parsed (text, meta) unit
        parsed_flat = parsed.flatten(parsed["_parsed"]).select(
            text=pw.apply(lambda p: p[0], pw.this["_parsed"]),
            _metadata=pw.apply(
                lambda p, m: {**(m or {}), **(p[1] or {})},
                pw.this["_parsed"],
                pw.this["_metadata"],
            ),
        )
        for post in self.doc_post_processors:
            parsed_flat = parsed_flat.select(
                text=pw.apply(lambda t, m, post=post: post(t, m)[0], pw.this.text, pw.this["_metadata"]),
                _metadata=pw.apply(lambda t, m, post=post: post(t, m)[1], pw.this.text, pw.this["_metadata"]),
            )
        chunked = parsed_flat.with_columns(
            _chunks=self._as_transformer_expr(self.splitter, parsed_flat.text)
        )
        chunks = chunked.flatten(chunked["_chunks"]).select(
            text=pw.apply(lambda c: c[0], pw.this["_chunks"]),
            metadata=pw.apply(
                lambda c, m: {**(m or {}), **(c[1] or {})},
                pw.this["_chunks"],
                pw.this["_metadata"],
            ),
        )
        self._chunks = chunks
        self._index = self.retriever_factory.build_data_index(
            chunks.text, chunks, metadata_column=chunks.metadata
        )

    @property
    def index(self) -> DataIndex:
        assert self._index is not None
        return self._index

    @property
    def input_table(self) -> Table:
        assert self._input_table is not None
        return self._input_table

    # ------------------------------------------------------------------
    # query surfaces (reference document_store.py:323-470)

    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)
        # multi-tenant serving: names the tenant for admission control /
        # SLO-class scheduling; absent → "default" tenant
        tenant: str | None = pw.column_definition(default_value=None)

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(pw.Schema):
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)

    def retrieve_query(self, queries: Table) -> Table:
        """reference ``document_store.py:426`` — returns a ``result`` column
        holding the matched docs as dicts sorted best-first."""
        merged = queries.with_columns(
            _pw_filter=pw.apply(
                _merge_filters, queries.metadata_filter, queries.filepath_globpattern
            )
        )
        replies = self.index.query_as_of_now(
            merged.query,
            number_of_matches=merged.k,
            metadata_filter=merged["_pw_filter"],
        )

        def to_docs(ids, scores, datas):
            out = []
            for _id, score, data in zip(ids or (), scores or (), datas or ()):
                d = dict(data or {})
                doc = {
                    "text": d.get("text", ""),
                    "metadata": d.get("metadata", {}),
                    "score": float(score),
                    "dist": -float(score),
                }
                out.append(doc)
            return out

        return replies.select(
            *[replies[c] for c in queries.column_names() if c in replies.column_names()],
            result=pw.apply(
                to_docs,
                replies["_pw_index_reply_id"],
                replies["_pw_index_reply_score"],
                replies["_pw_index_reply"],
            ),
        )

    def statistics_query(self, queries: Table) -> Table:
        """reference ``document_store.py:323`` — indexed file statistics."""
        stats = self.input_table.reduce(
            count=pw.reducers.count(),
            last_modified=pw.reducers.max(
                pw.apply(
                    lambda m: (m or {}).get("modified_at", 0), pw.this["_metadata"]
                )
            ),
        )
        # cross join (no on-conditions): every query row gets the one stats row
        return queries.join_left(stats, id=queries.id).select(
            result=pw.apply(
                lambda c, lm: {
                    "file_count": int(c or 0),
                    "last_modified": lm,
                    "last_indexed": lm,
                },
                pw.right.count,
                pw.right.last_modified,
            ),
        )

    def inputs_query(self, queries: Table) -> Table:
        """reference ``document_store.py:385`` — list indexed input files."""
        files = self.input_table.reduce(
            result=pw.reducers.tuple(
                pw.apply(lambda m: dict(m or {}), pw.this["_metadata"])
            )
        )

        def filter_files(result, metadata_filter, globpattern):
            items = [dict(m) for m in (result or ())]
            merged = _merge_filters(metadata_filter, globpattern)
            if merged is not None:
                items = [m for m in items if merged(m)]
            return items

        return queries.join_left(files, id=queries.id).select(
            result=pw.apply(
                filter_files,
                pw.right.result,
                pw.left.metadata_filter,
                pw.left.filepath_globpattern,
            ),
        )


class SlidesDocumentStore(DocumentStore):
    """Slide-deck variant (reference ``document_store.py:471``); adds the
    parsed-docs listing surface."""

    def parsed_documents_query(self, queries: Table) -> Table:
        assert self._chunks is not None
        docs = self._chunks.reduce(
            result=pw.reducers.tuple(
                pw.apply(
                    lambda t, m: {"text": t, "metadata": dict(m or {})},
                    pw.this.text,
                    pw.this.metadata,
                )
            )
        )
        return queries.join_left(docs, id=queries.id).select(result=pw.right.result)
