"""Extension packs (reference ``python/pathway/xpacks/``)."""

from typing import Any


def __getattr__(name: str) -> Any:
    import importlib

    if name in ("llm",):
        return importlib.import_module(f"pathway_tpu.xpacks.{name}")
    raise AttributeError(name)
