"""SharePoint document connector (reference
``python/pathway/xpacks/connectors/sharepoint/__init__.py``, 376 LoC,
license-gated Office365 client).

One row per file under ``root_path``: binary ``data`` plus ``_metadata``
(created_at / modified_at / path / size / status), re-emitted (upsert by
path) when a file's modified time or size changes, deleted when it
vanishes — the same streaming contract as the reference's subject.

The transport is injectable: pass ``connection=`` with a duck-typed
client — ``list_files(root_path) -> [entry]`` where each entry exposes
``path/size/created_at/modified_at``, and ``download(path) -> bytes``.
Without one, the ``office365`` ClientContext is imported lazily (absent
in this environment; certificate auth args mirror the reference).
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, input_table
from pathway_tpu.io._gated import MissingDependency

__all__ = ["read", "FileEntry"]

STATUS_DOWNLOADED = "downloaded"
STATUS_SIZE_LIMIT_EXCEEDED = "size_limit_exceeded"


@dataclasses.dataclass
class FileEntry:
    """Listing entry the injectable connection yields."""

    path: str
    size: int
    created_at: int = 0
    modified_at: int = 0


class _Office365Connection:
    """Adapter over the office365 client (reference ClientContext flow:
    ``with_client_certificate`` + folder traversal + ``download``)."""

    def __init__(self, url, tenant, client_id, cert_path, thumbprint):
        try:
            from office365.sharepoint.client_context import (  # type: ignore[import-not-found]
                ClientContext,
            )
        except ImportError as e:
            raise MissingDependency(
                "office365-rest-python-client is not installed; pass "
                "connection= with a list_files/download-capable object"
            ) from e
        self._ctx = ClientContext(url).with_client_certificate(
            tenant, client_id, thumbprint=thumbprint, cert_path=cert_path
        )

    def list_files(self, root_path: str) -> list[FileEntry]:
        folder = self._ctx.web.get_folder_by_server_relative_path(root_path)
        files = folder.get_files(recursive=True).execute_query()
        out = []
        for f in files:
            out.append(
                FileEntry(
                    path=f.properties["ServerRelativeUrl"],
                    size=int(f.length or 0),
                    created_at=int(f.time_created.timestamp()),
                    modified_at=int(f.time_last_modified.timestamp()),
                )
            )
        return out

    def download(self, path: str) -> bytes:
        import io

        buf = io.BytesIO()
        self._ctx.web.get_file_by_server_relative_path(path).download(
            buf
        ).execute_query()
        return buf.getvalue()


class _SharePointSource(RowSource):
    deterministic_replay = True

    def __init__(
        self,
        connection: Any,
        root_path: str,
        *,
        mode: str = "streaming",
        refresh_interval: float = 30.0,
        object_size_limit: int | None = None,
        with_metadata: bool = True,
    ):
        self.connection = connection
        self.root_path = root_path
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.object_size_limit = object_size_limit
        self.with_metadata = with_metadata

    def _meta(self, entry: FileEntry, status: str) -> dict:
        return {
            "created_at": entry.created_at,
            "modified_at": entry.modified_at,
            "path": entry.path,
            "size": entry.size,
            "seen_at": int(_time.time()),
            "status": status,
        }

    def run(self, events: Any) -> None:
        seen: dict[str, tuple] = {}  # path -> (modified_at, size)
        while True:
            emitted = False
            current: set[str] = set()
            for entry in self.connection.list_files(self.root_path):
                current.add(entry.path)
                ver = (entry.modified_at, entry.size)
                if seen.get(entry.path) == ver:
                    continue
                if (
                    self.object_size_limit is not None
                    and entry.size > self.object_size_limit
                ):
                    # reference contract: oversized files appear with an
                    # explicit status and empty payload, not silently
                    data = b""
                    status = STATUS_SIZE_LIMIT_EXCEEDED
                else:
                    data = self.connection.download(entry.path)
                    status = STATUS_DOWNLOADED
                row: tuple = (data,)
                if self.with_metadata:
                    row = (data, self._meta(entry, status))
                events.add(ref_scalar("__sharepoint__", entry.path), row)
                seen[entry.path] = ver
                emitted = True
            for path in list(seen):
                if path not in current:
                    del seen[path]
                    events.remove(ref_scalar("__sharepoint__", path), ())
                    emitted = True
            if emitted:
                events.commit()
            if self.mode == "static":
                return
            if events.stopped:
                return
            _time.sleep(self.refresh_interval)


def read(
    url: str = "",
    *,
    tenant: str = "",
    client_id: str = "",
    cert_path: str | None = None,
    thumbprint: str | None = None,
    root_path: str = "",
    mode: str = "streaming",
    refresh_interval: int = 30,
    object_size_limit: int | None = None,
    with_metadata: bool = True,
    connection: Any = None,
    name: str = "sharepoint",
    **kwargs: Any,
) -> Table:
    """One row per SharePoint file under ``root_path``."""
    # licensed xpack (reference gates SharePoint behind the license too);
    # demo keys carry the entitlement so evaluation works offline
    from pathway_tpu.internals.license import check_entitlements

    check_entitlements("xpack-sharepoint")
    if connection is None:
        connection = _Office365Connection(url, tenant, client_id, cert_path, thumbprint)
    if with_metadata:
        schema = sch.schema_from_types(data=bytes, _metadata=dict)
    else:
        schema = sch.schema_from_types(data=bytes)
    src = _SharePointSource(
        connection,
        root_path,
        mode=mode,
        refresh_interval=float(refresh_interval),
        object_size_limit=object_size_limit,
        with_metadata=with_metadata,
    )
    return input_table(src, schema, name=name, upsert=True)
