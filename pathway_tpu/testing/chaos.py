"""Deterministic, seedable fault-injection harness for chaos testing.

Drives the crash-recovery drills in ``tests/test_chaos_recovery.py`` and
is usable against real pipelines: every fault is injected by
monkey-patching a *specific* call site under a context manager, so a test
reads as "this exact operation fails on its Nth invocation" — no sleeps,
no racing kill signals, fully reproducible under a fixed ``seed``.

Fault classes (mirrors the failure modes the supervisor and persistence
layers must survive):

- :meth:`chaos.raise_on_nth_call` — transient exception on the Nth call.
- :meth:`chaos.inject_latency` — fixed or seeded-random delay per call
  (exercises watchdogs and autocommit timers).
- :meth:`chaos.torn_write` — an ``_FsBackend.append`` that writes a
  *partial* record then dies (crash mid-append; replay must treat the
  torn tail as absent).
- :meth:`chaos.crash_between_snapshot_and_commit` — the operator
  snapshot is persisted, then the process "dies" before the run
  continues (resume must not double-apply).

Usage::

    from pathway_tpu.testing import chaos

    with chaos(seed=7) as c:
        c.raise_on_nth_call(SomeReader, "poll", n=3)
        run_pipeline()
    assert c.call_count(SomeReader, "poll") >= 3
"""

from __future__ import annotations

import functools
import random
import threading
import time as _time
from typing import Any, Callable, Iterable

__all__ = ["ChaosError", "chaos", "flaky_once"]


class ChaosError(RuntimeError):
    """The marker exception raised by injected faults."""


class chaos:
    """Seedable fault-injection context manager (restores every patch on
    exit, even when the body raises)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        #: (owner, attr, original) in application order
        self._patches: list[tuple[Any, str, Any]] = []
        #: one counter PER PATCH (faults may stack on the same attr; a
        #: shared per-attr counter would double-count each call)
        self._counters: dict[tuple[int, str, int], int] = {}
        self._lock = threading.Lock()
        self._entered = False

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "chaos":
        self._entered = True
        return self

    def __exit__(self, *exc: Any) -> None:
        self.restore()

    def restore(self) -> None:
        """Undo every patch (reverse order)."""
        while self._patches:
            owner, attr, orig = self._patches.pop()
            setattr(owner, attr, orig)

    # -- bookkeeping ----------------------------------------------------
    def _counter_key(self, owner: Any, attr: str) -> tuple[int, str, int]:
        """Reserve a fresh counter slot for one patch."""
        return (id(owner), attr, len(self._patches))

    def _bump(self, key: tuple[int, str, int]) -> int:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + 1
            return self._counters[key]

    def call_count(self, owner: Any, attr: str) -> int:
        """How many times the patched ``owner.attr`` was invoked (with
        stacked faults each call passes through every layer once, so the
        max across this attr's patch counters is the invocation count)."""
        with self._lock:
            return max(
                (
                    v
                    for (oid, a, _i), v in self._counters.items()
                    if oid == id(owner) and a == attr
                ),
                default=0,
            )

    def _patch(self, owner: Any, attr: str, replacement: Callable) -> None:
        orig = getattr(owner, attr)
        self._patches.append((owner, attr, orig))
        setattr(owner, attr, replacement)

    # -- faults ---------------------------------------------------------
    def raise_on_nth_call(
        self,
        owner: Any,
        attr: str,
        n: int,
        exc_factory: Callable[[], BaseException] | None = None,
        every: bool = False,
    ) -> None:
        """The Nth invocation (1-based) of ``owner.attr`` raises; with
        ``every=True`` every invocation from the Nth on raises (a
        permanent fault instead of a transient one)."""
        orig = getattr(owner, attr)
        key = self._counter_key(owner, attr)
        make_exc = exc_factory or (
            lambda: ChaosError(f"injected fault: {attr} call #{n}")
        )

        @functools.wraps(orig)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            count = self._bump(key)
            if count == n or (every and count >= n):
                raise make_exc()
            return orig(*args, **kwargs)

        self._patch(owner, attr, wrapper)

    def inject_latency(
        self,
        owner: Any,
        attr: str,
        delay_s: float = 0.05,
        jitter_s: float = 0.0,
        limit: int | None = None,
    ) -> None:
        """Sleep before each call of ``owner.attr`` (``delay_s`` plus a
        seeded uniform draw from ``[0, jitter_s]``); ``limit`` bounds how
        many calls are delayed."""
        orig = getattr(owner, attr)
        key = self._counter_key(owner, attr)

        @functools.wraps(orig)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            count = self._bump(key)
            if limit is None or count <= limit:
                _time.sleep(delay_s + self.rng.uniform(0.0, jitter_s))
            return orig(*args, **kwargs)

        self._patch(owner, attr, wrapper)

    def torn_write(
        self,
        backend_impl: Any,
        on_nth: int = 1,
        keep_fraction: float = 0.5,
    ) -> None:
        """The Nth ``append`` on a filesystem persistence backend writes
        the length header plus only ``keep_fraction`` of the payload,
        then raises :class:`ChaosError` — exactly what a crash mid-append
        leaves on disk.  ``read_all``/``replay_events`` must treat the
        torn tail as absent."""
        orig = backend_impl.append
        key = self._counter_key(backend_impl, "append")

        def wrapper(stream: str, record: bytes, durable: bool = True) -> None:
            count = self._bump(key)
            if count != on_nth:
                return orig(stream, record, durable)
            # write a torn record exactly as _FsBackend lays them out:
            # full length header, truncated payload, no trailing bytes
            keep = max(0, min(len(record) - 1, int(len(record) * keep_fraction)))
            with backend_impl._lock:
                backend_impl._offsets.pop(stream, None)
                f = backend_impl._handle(stream)
                f.write(len(record).to_bytes(8, "little"))
                f.write(record[:keep])
                f.flush()
                backend_impl._drop_handle(stream)
            raise ChaosError(
                f"injected torn write on stream {stream!r} (append #{count})"
            )

        self._patch(backend_impl, "append", wrapper)

    def crash_between_snapshot_and_commit(self, hooks: Any, on_nth: int = 1) -> None:
        """``PersistenceHooks.save_operator_snapshot`` persists the
        snapshot blob, then raises — the crash window between an operator
        snapshot landing on disk and the run carrying on.  Resume from
        that snapshot must replay only the committed tail (no loss, no
        double-apply)."""
        orig = hooks.save_operator_snapshot
        key = self._counter_key(hooks, "save_operator_snapshot")

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            count = self._bump(key)
            result = orig(*args, **kwargs)
            if count == on_nth:
                raise ChaosError(
                    f"injected crash after operator snapshot #{count}"
                )
            return result

        self._patch(hooks, "save_operator_snapshot", wrapper)


def flaky_once(
    items: Iterable[Any],
    fail_before_index: int,
    exc_factory: Callable[[], BaseException] | None = None,
) -> Callable[[], Iterable[Any]]:
    """Generator factory for a transiently-faulty source: the FIRST pass
    raises just before yielding item ``fail_before_index``; every later
    pass yields all items.  Pairs with a deterministic-replay reader +
    :class:`~pathway_tpu.internals.resilience.ConnectorRecoveryPolicy`
    to drill restart-with-resume (each row delivered exactly once)."""
    items = list(items)
    state = {"tripped": False}
    make_exc = exc_factory or (
        lambda: ChaosError(f"injected source fault before row {fail_before_index}")
    )

    def gen() -> Iterable[Any]:
        for i, item in enumerate(items):
            if not state["tripped"] and i == fail_before_index:
                state["tripped"] = True
                raise make_exc()
            yield item

    return gen
