"""Deterministic, seedable fault-injection harness for chaos testing.

Drives the crash-recovery drills in ``tests/test_chaos_recovery.py`` and
is usable against real pipelines: every fault is injected by
monkey-patching a *specific* call site under a context manager, so a test
reads as "this exact operation fails on its Nth invocation" — no sleeps,
no racing kill signals, fully reproducible under a fixed ``seed``.

Fault classes (mirrors the failure modes the supervisor and persistence
layers must survive):

- :meth:`chaos.raise_on_nth_call` — transient exception on the Nth call.
- :meth:`chaos.inject_latency` — fixed or seeded-random delay per call
  (exercises watchdogs and autocommit timers).
- :meth:`chaos.torn_write` — an ``_FsBackend.append`` that writes a
  *partial* record then dies (crash mid-append; replay must treat the
  torn tail as absent).
- :meth:`chaos.crash_between_snapshot_and_commit` — the operator
  snapshot is persisted, then the process "dies" before the run
  continues (resume must not double-apply).

Cluster fault primitives (drive ``tests/test_cluster_recovery.py``):

- :meth:`chaos.kill_worker` — a chosen worker rank dies at the start of
  its Nth epoch (``ChaosError`` or a hard ``os._exit`` — the latter is
  what a real SIGKILL looks like to the rest of the mesh).
- :meth:`chaos.kill_worker_mid_merge` — the process hosting a chosen
  rank dies in the instant between a finished background index merge
  and its atomic commit (``SegmentedIndex._pre_commit``), the widest
  crash window online index maintenance has.
- :meth:`chaos.delay_exchange_frames` / :meth:`chaos.drop_exchange_frames`
  — latency or loss injected at the peer link's single egress point
  (``_PeerSender._transmit``); dropping mutes heartbeats too, so a muted
  peer becomes *detectably* dead.

Gray-failure primitives (the failures that are NOT clean crashes —
asymmetric, partial, or slow — the modes membership layers classically
misdiagnose):

- :meth:`chaos.asymmetric_partition` — delay or drop frames in exactly
  ONE direction (``src -> dst``); the reverse path stays perfect, so
  ``src`` looks dead to ``dst`` while ``dst`` looks fine to ``src``.
- :meth:`chaos.pause_resume` — SIGSTOP a live OS process and SIGCONT it
  after a pause: the process is silent (no heartbeats, no frames, no
  exit code) then wakes and resumes sending as if nothing happened —
  exactly a long GC pause / VM migration.  Survivors must mark it
  suspect/dead and then handle the stale frames that resume on wake.
- :meth:`chaos.slow_peer` — every outbound transmission from one rank is
  slowed (seeded jitter): a degraded-but-alive peer that drags epochs
  without ever missing a liveness deadline.
Overload primitives (drive ``tests/test_overload.py`` and
``bench.py bench_overload`` — sustained pressure rather than failure):

- :meth:`chaos.firehose_source` — a seedable synthetic source pushing
  rows at a target rate (or flat-out); when the ingest credit buffer
  fills, its ``next()`` calls park inside the connector queue's
  ``charge`` — the backpressure path under test.
- :meth:`chaos.stall_sink` — every sink delivery
  (``OutputNode.process``) with data sleeps: a wedged downstream
  writer.  Sinks are synchronous with the epoch cut, so the stall
  holds the drain loop and pressure propagates back to the sources.
- :meth:`chaos.slow_consumer` — one worker rank's epochs take
  ``factor``× their real time: a degraded-but-alive *consumer* whose
  exchange mailboxes back up, exercising sender-side credit
  (``PATHWAY_EXCHANGE_CREDIT_BYTES``) instead of liveness isolation.

- :class:`ClusterDrill` — seedable end-to-end drill: run a wordcount
  cluster fault-free, re-run it with a worker killed at a random epoch
  under :class:`~pathway_tpu.internals.resilience.ClusterSupervisor`,
  and assert the recovered output is byte-identical.
- :class:`IndexDrill` — the live-index variant: a vector index under
  upsert churn, killed mid-merge, must recover with exactly-once
  upserts (index size equals the distinct doc count — nothing dropped,
  nothing double-applied) and recall over the final corpus.

Usage::

    from pathway_tpu.testing import chaos

    with chaos(seed=7) as c:
        c.raise_on_nth_call(SomeReader, "poll", n=3)
        run_pipeline()
    assert c.call_count(SomeReader, "poll") >= 3
"""

from __future__ import annotations

import functools
import os
import random
import threading
import time as _time
from typing import Any, Callable, Iterable

__all__ = ["ChaosError", "ClusterDrill", "IndexDrill", "chaos", "flaky_once"]


class ChaosError(RuntimeError):
    """The marker exception raised by injected faults."""


class chaos:
    """Seedable fault-injection context manager (restores every patch on
    exit, even when the body raises)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        #: (owner, attr, original) in application order
        self._patches: list[tuple[Any, str, Any]] = []
        #: one counter PER PATCH (faults may stack on the same attr; a
        #: shared per-attr counter would double-count each call)
        self._counters: dict[tuple[int, str, int], int] = {}
        self._lock = threading.Lock()
        self._entered = False

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "chaos":
        self._entered = True
        return self

    def __exit__(self, *exc: Any) -> None:
        self.restore()

    def restore(self) -> None:
        """Undo every patch (reverse order)."""
        while self._patches:
            owner, attr, orig = self._patches.pop()
            setattr(owner, attr, orig)

    # -- bookkeeping ----------------------------------------------------
    def _counter_key(self, owner: Any, attr: str) -> tuple[int, str, int]:
        """Reserve a fresh counter slot for one patch."""
        return (id(owner), attr, len(self._patches))

    def _bump(self, key: tuple[int, str, int]) -> int:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + 1
            return self._counters[key]

    def call_count(self, owner: Any, attr: str) -> int:
        """How many times the patched ``owner.attr`` was invoked (with
        stacked faults each call passes through every layer once, so the
        max across this attr's patch counters is the invocation count)."""
        with self._lock:
            return max(
                (
                    v
                    for (oid, a, _i), v in self._counters.items()
                    if oid == id(owner) and a == attr
                ),
                default=0,
            )

    def _patch(self, owner: Any, attr: str, replacement: Callable) -> None:
        orig = getattr(owner, attr)
        self._patches.append((owner, attr, orig))
        setattr(owner, attr, replacement)

    # -- faults ---------------------------------------------------------
    def raise_on_nth_call(
        self,
        owner: Any,
        attr: str,
        n: int,
        exc_factory: Callable[[], BaseException] | None = None,
        every: bool = False,
    ) -> None:
        """The Nth invocation (1-based) of ``owner.attr`` raises; with
        ``every=True`` every invocation from the Nth on raises (a
        permanent fault instead of a transient one)."""
        orig = getattr(owner, attr)
        key = self._counter_key(owner, attr)
        make_exc = exc_factory or (
            lambda: ChaosError(f"injected fault: {attr} call #{n}")
        )

        @functools.wraps(orig)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            count = self._bump(key)
            if count == n or (every and count >= n):
                raise make_exc()
            return orig(*args, **kwargs)

        self._patch(owner, attr, wrapper)

    def inject_latency(
        self,
        owner: Any,
        attr: str,
        delay_s: float = 0.05,
        jitter_s: float = 0.0,
        limit: int | None = None,
    ) -> None:
        """Sleep before each call of ``owner.attr`` (``delay_s`` plus a
        seeded uniform draw from ``[0, jitter_s]``); ``limit`` bounds how
        many calls are delayed."""
        orig = getattr(owner, attr)
        key = self._counter_key(owner, attr)

        @functools.wraps(orig)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            count = self._bump(key)
            if limit is None or count <= limit:
                _time.sleep(delay_s + self.rng.uniform(0.0, jitter_s))
            return orig(*args, **kwargs)

        self._patch(owner, attr, wrapper)

    def torn_write(
        self,
        backend_impl: Any,
        on_nth: int = 1,
        keep_fraction: float = 0.5,
    ) -> None:
        """The Nth ``append`` on a filesystem persistence backend writes
        the length header plus only ``keep_fraction`` of the payload,
        then raises :class:`ChaosError` — exactly what a crash mid-append
        leaves on disk.  ``read_all``/``replay_events`` must treat the
        torn tail as absent."""
        orig = backend_impl.append
        key = self._counter_key(backend_impl, "append")

        def wrapper(stream: str, record: bytes, durable: bool = True) -> None:
            count = self._bump(key)
            if count != on_nth:
                return orig(stream, record, durable)
            # write a torn record exactly as _FsBackend lays them out:
            # full length header, truncated payload, no trailing bytes
            keep = max(0, min(len(record) - 1, int(len(record) * keep_fraction)))
            with backend_impl._lock:
                backend_impl._offsets.pop(stream, None)
                f = backend_impl._handle(stream)
                f.write(len(record).to_bytes(8, "little"))
                f.write(record[:keep])
                f.flush()
                backend_impl._drop_handle(stream)
            raise ChaosError(
                f"injected torn write on stream {stream!r} (append #{count})"
            )

        self._patch(backend_impl, "append", wrapper)

    def crash_between_snapshot_and_commit(self, hooks: Any, on_nth: int = 1) -> None:
        """An operator snapshot persists, then the process "dies" before
        the run carries on — the crash window between a checkpoint landing
        on disk and the epoch loop continuing.  Resume from that snapshot
        must replay only the committed tail (no loss, no double-apply).

        Counts the synchronous (``save_operator_snapshot``) and
        asynchronous (``save_operator_snapshot_async``, used by periodic
        checkpoints) paths on ONE shared counter; on the async path the
        queued blob is flushed to disk before the injected death so the
        crash window is identical in both cases."""
        shared = {"count": 0}
        shared_lock = threading.Lock()

        def _next() -> int:
            with shared_lock:
                shared["count"] += 1
                return shared["count"]

        orig_sync = hooks.save_operator_snapshot
        key_sync = self._counter_key(hooks, "save_operator_snapshot")

        def wrapper_sync(*args: Any, **kwargs: Any) -> Any:
            self._bump(key_sync)
            count = _next()
            result = orig_sync(*args, **kwargs)
            if count == on_nth:
                raise ChaosError(
                    f"injected crash after operator snapshot #{count}"
                )
            return result

        self._patch(hooks, "save_operator_snapshot", wrapper_sync)

        orig_async = getattr(hooks, "save_operator_snapshot_async", None)
        if orig_async is None:
            return
        key_async = self._counter_key(hooks, "save_operator_snapshot_async")

        def wrapper_async(*args: Any, **kwargs: Any) -> Any:
            self._bump(key_async)
            count = _next()
            result = orig_async(*args, **kwargs)
            if count == on_nth:
                flush = getattr(hooks, "flush_checkpoints", None)
                if flush is not None:
                    flush()  # the snapshot must be ON DISK when we "die"
                raise ChaosError(
                    f"injected crash after operator snapshot #{count}"
                )
            return result

        self._patch(hooks, "save_operator_snapshot_async", wrapper_async)

    # -- cluster faults -------------------------------------------------
    def kill_worker(
        self,
        rank: int,
        at_epoch: int,
        hard: bool = False,
        generation: int = 0,
        exit_code: int = 70,
    ) -> None:
        """Worker ``rank`` dies at the start of its ``at_epoch``-th epoch
        (1-based; earlier epochs complete and may have checkpointed).

        ``hard=True`` calls ``os._exit(exit_code)`` — no unwinding, no
        atexit, exactly what SIGKILL looks like to the peer mesh and the
        supervisor; otherwise a :class:`ChaosError` unwinds the worker
        (covers the fatal-operator-error path).  ``generation`` arms the
        fault only in that supervisor respawn generation (matched against
        ``PATHWAY_WORKER_RESTARTS``), so a restarted cluster does not
        re-kill itself forever."""
        from pathway_tpu.engine.scheduler import Scheduler

        if int(os.environ.get("PATHWAY_WORKER_RESTARTS", "0")) != generation:
            return  # a later generation: the fault already fired and is spent
        orig = Scheduler.run_epoch
        key = self._counter_key(Scheduler, "run_epoch")
        epochs_by_rank: dict[int, int] = {}
        rank_lock = threading.Lock()

        @functools.wraps(orig)
        def wrapper(sched: Any, time: int, inject: Any, **kwargs: Any) -> Any:
            self._bump(key)
            ctx = kwargs.get("ctx") or sched.ctx
            my_rank = getattr(ctx, "worker_id", 0)
            with rank_lock:
                epochs_by_rank[my_rank] = epochs_by_rank.get(my_rank, 0) + 1
                count = epochs_by_rank[my_rank]
            if my_rank == rank and count == at_epoch:
                if hard:
                    # the whole point of the flight recorder: the dying
                    # process's spans survive an os._exit (which skips
                    # atexit) because we flush the rings right here
                    from pathway_tpu.internals import tracing as _tracing

                    _tracing.flush("chaos_kill")
                    os._exit(exit_code)
                raise ChaosError(
                    f"injected worker death: rank {rank} at epoch #{count}"
                )
            return orig(sched, time, inject, **kwargs)

        self._patch(Scheduler, "run_epoch", wrapper)

    def kill_worker_mid_merge(
        self,
        rank: int,
        on_nth_merge: int = 1,
        generation: int = 0,
        exit_code: int = 71,
    ) -> None:
        """The process hosting worker ``rank`` dies (hard ``os._exit``)
        in the instant between a finished background index merge and its
        atomic commit — :meth:`SegmentedIndex._pre_commit`, the widest
        crash window online index maintenance has: the merge work is
        done but none of it is published, and the last checkpoint holds
        the pre-merge segmentation.  Recovery must restore that
        checkpoint, replay the connector tail (idempotent upserts), and
        simply re-merge — nothing lost, nothing double-applied.

        ``on_nth_merge`` counts merge commits within the armed process
        (1-based); ``generation`` arms the fault only in that supervisor
        respawn generation (vs ``PATHWAY_WORKER_RESTARTS``) so the
        restarted cluster does not re-kill itself forever.  The rank is
        matched against ``PATHWAY_PROCESS_ID`` at arm time: the merge
        runs on a maintenance thread with no worker context, so the
        fault is scoped per process, not per in-process thread."""
        from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

        if int(os.environ.get("PATHWAY_WORKER_RESTARTS", "0")) != generation:
            return  # a later generation: the fault already fired and is spent
        if int(os.environ.get("PATHWAY_PROCESS_ID", "0")) != rank:
            return
        orig = SegmentedIndex._pre_commit
        key = self._counter_key(SegmentedIndex, "_pre_commit")

        @functools.wraps(orig)
        def wrapper(seg: Any) -> Any:
            count = self._bump(key)
            if count == on_nth_merge:
                from pathway_tpu.internals import tracing as _tracing

                _tracing.flush("chaos_kill")  # os._exit skips atexit
                os._exit(exit_code)
            return orig(seg)

        self._patch(SegmentedIndex, "_pre_commit", wrapper)

    def delay_exchange_frames(
        self,
        delay_s: float = 0.05,
        jitter_s: float = 0.0,
        limit: int | None = None,
        process_id: int | None = None,
    ) -> None:
        """Sleep before every outbound cluster transmission (data frames
        AND heartbeats) — a slow or congested link.  ``process_id``
        restricts the fault to links owned by one process; ``limit``
        bounds how many transmissions are delayed."""
        from pathway_tpu.engine.cluster import _PeerSender

        orig = _PeerSender._transmit
        key = self._counter_key(_PeerSender, "_transmit")

        @functools.wraps(orig)
        def wrapper(sender: Any, body: Any, n_frames: int) -> Any:
            count = self._bump(key)
            mine = (
                process_id is None
                or getattr(sender.links, "process_id", None) == process_id
            )
            if mine and (limit is None or count <= limit):
                _time.sleep(delay_s + self.rng.uniform(0.0, jitter_s))
            return orig(sender, body, n_frames)

        self._patch(_PeerSender, "_transmit", wrapper)

    def drop_exchange_frames(
        self,
        after: int = 0,
        process_id: int | None = None,
        peer: int | None = None,
    ) -> None:
        """Silently drop every outbound transmission past the first
        ``after`` — a one-way partition.  Dropping happens at the link's
        single egress point, so heartbeats are muted along with data: the
        muted process turns *detectably* dead (liveness timeout) rather
        than silently lossy.  ``process_id``/``peer`` scope the fault to
        one process's links or one destination."""
        from pathway_tpu.engine.cluster import _PeerSender

        orig = _PeerSender._transmit
        key = self._counter_key(_PeerSender, "_transmit")

        @functools.wraps(orig)
        def wrapper(sender: Any, body: Any, n_frames: int) -> Any:
            count = self._bump(key)
            mine = (
                process_id is None
                or getattr(sender.links, "process_id", None) == process_id
            ) and (peer is None or sender.peer == peer)
            if mine and count > after:
                return None  # swallowed by the injected partition
            return orig(sender, body, n_frames)

        self._patch(_PeerSender, "_transmit", wrapper)

    # -- gray failures ---------------------------------------------------
    def asymmetric_partition(
        self,
        src: int,
        dst: int,
        mode: str = "drop",
        delay_s: float = 0.2,
        jitter_s: float = 0.0,
        after: int = 0,
    ) -> None:
        """Break exactly ONE direction of one link: frames from process
        ``src`` to process ``dst`` are dropped (``mode="drop"``) or
        delayed (``mode="delay"``, plus a seeded uniform draw from
        ``[0, jitter_s]``) past the first ``after`` transmissions, while
        ``dst -> src`` stays perfect.

        This is the canonical gray failure: ``dst`` stops hearing
        heartbeats and declares ``src`` suspect/dead, while ``src`` still
        receives from ``dst`` and believes the mesh is whole.  Under the
        isolate fail policy the two sides may hold *different* membership
        views — which is exactly what the drill should assert about."""
        if mode not in ("drop", "delay"):
            raise ValueError(f"mode must be 'drop' or 'delay', got {mode!r}")
        from pathway_tpu.engine.cluster import _PeerSender

        orig = _PeerSender._transmit
        key = self._counter_key(_PeerSender, "_transmit")

        @functools.wraps(orig)
        def wrapper(sender: Any, body: Any, n_frames: int) -> Any:
            count = self._bump(key)
            mine = (
                getattr(sender.links, "process_id", None) == src
                and sender.peer == dst
            )
            if mine and count > after:
                if mode == "drop":
                    return None  # one-way black hole
                _time.sleep(delay_s + self.rng.uniform(0.0, jitter_s))
            return orig(sender, body, n_frames)

        self._patch(_PeerSender, "_transmit", wrapper)

    def pause_resume(
        self, pid: int, pause_s: float = 1.0
    ) -> threading.Timer:
        """SIGSTOP OS process ``pid`` now; SIGCONT it ``pause_s`` seconds
        later (from a daemon timer).  During the pause the process emits
        nothing — no heartbeats, no frames, no exit status — then wakes
        and resumes mid-instruction, the shape of a long GC pause, a VM
        live-migration, or an operator's stray ``kill -STOP``.

        Unlike the monkey-patching faults this targets a *separate* OS
        process (monkey patches don't cross process boundaries), so it is
        the primitive for supervisor/membership drills over real worker
        processes.  Returns the SIGCONT timer; :meth:`restore` (and so
        the context-manager exit) also fires any pending SIGCONT so a
        failing test never leaks a stopped process."""
        import signal

        os.kill(pid, signal.SIGSTOP)
        fired = threading.Event()

        def _resume() -> None:
            if fired.is_set():
                return
            fired.set()
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass  # it died while paused; nothing to resume

        timer = threading.Timer(pause_s, _resume)
        timer.daemon = True
        timer.start()
        # ride the patch-restore machinery: "restoring" this fault means
        # making sure the SIGCONT has been delivered
        self._patches.append((_ResumeOnRestore(timer, _resume), "noop", None))
        return timer

    def slow_peer(
        self,
        process_id: int,
        delay_s: float = 0.05,
        jitter_s: float = 0.02,
    ) -> None:
        """Every outbound transmission from ``process_id`` (to every
        peer) is slowed by ``delay_s`` plus a seeded uniform draw from
        ``[0, jitter_s]`` — a degraded-but-alive rank: it keeps making
        its liveness deadlines while dragging every epoch and probe it
        participates in.  The fault the hedged-collect path
        (``PartitionedIndex`` with ``hedge_timeout_s``) exists for."""
        self.delay_exchange_frames(
            delay_s=delay_s, jitter_s=jitter_s, process_id=process_id
        )

    # -- overload primitives ---------------------------------------------
    def stall_sink(
        self,
        seconds: float,
        limit: int | None = None,
        name: str | None = None,
    ) -> None:
        """Every sink delivery that carries data sleeps ``seconds`` — a
        wedged downstream writer (full disk, throttled API, dead
        consumer).  Patches :meth:`OutputNode.process`, the synchronous
        sink dispatch: the stall holds the epoch cut, the drain loop
        stops taking from the connector queues, the ingest credit buffer
        fills, and the readers park — end-to-end pressure propagation
        with zero data loss under ``on_overflow="pause"``.

        ``limit`` bounds how many deliveries stall (then the sink
        recovers); ``name`` scopes the fault to sinks whose node name
        contains it (default: every sink)."""
        from pathway_tpu.engine.graph import OutputNode

        orig = OutputNode.process
        key = self._counter_key(OutputNode, "process")

        @functools.wraps(orig)
        def wrapper(node: Any, ctx: Any, time: int, inbatches: Any) -> Any:
            count = self._bump(key)
            mine = name is None or name in getattr(node, "name", "")
            if mine and inbatches and inbatches[0]:
                if limit is None or count <= limit:
                    _time.sleep(seconds)
            return orig(node, ctx, time, inbatches)

        self._patch(OutputNode, "process", wrapper)

    def firehose_source(
        self,
        rows_per_sec: float | None,
        total_rows: int,
        vocab: int = 32,
        payload_bytes: int = 64,
        commit_every: int = 64,
        row_factory: Callable[[random.Random, int], dict] | None = None,
    ) -> Any:
        """A seedable synthetic source pushing ``total_rows`` rows at
        ``rows_per_sec`` (``None`` or ``<= 0``: flat-out, the true
        firehose).  Returns a :class:`~pathway_tpu.io.python.ConnectorSubject`
        for ``pw.io.python.read``; default rows are
        ``{"word": "w<k>", "payload": "<payload_bytes of x>"}`` with the
        word drawn from a per-source seeded RNG, or supply
        ``row_factory(rng, i)`` for a custom shape.

        When the source outruns the pipeline and the ingest credit
        buffer (``PATHWAY_INGEST_BUFFER_BYTES``) fills, ``next()`` parks
        inside the connector queue's byte accounting — the reader slows
        to the drain rate instead of growing RSS.  Cuts an epoch every
        ``commit_every`` rows and polls ``stopped`` so shutdown is
        prompt even mid-burst."""
        from pathway_tpu.io.python import ConnectorSubject

        rng = random.Random(self.rng.randrange(2**31))
        interval = (
            1.0 / rows_per_sec if rows_per_sec and rows_per_sec > 0 else 0.0
        )

        class _Firehose(ConnectorSubject):
            def run(subject) -> None:
                start = _time.monotonic()
                for i in range(total_rows):
                    if subject.stopped:
                        return
                    if row_factory is not None:
                        subject.next(**row_factory(rng, i))
                    else:
                        subject.next(
                            word=f"w{rng.randrange(vocab)}",
                            payload="x" * payload_bytes,
                        )
                    if (i + 1) % commit_every == 0:
                        subject.commit()
                    if interval:
                        # pace against the wall clock, not per-row sleeps:
                        # a backpressure pause already "paid" the wait
                        lag = start + (i + 1) * interval - _time.monotonic()
                        if lag > 0:
                            _time.sleep(lag)
                subject.commit()

        return _Firehose(datasource_name="firehose")

    def slow_consumer(self, rank: int, factor: float = 3.0) -> None:
        """Worker ``rank``'s epochs take ``factor``× their real time
        (each :meth:`Scheduler.run_epoch` is followed by a sleep of
        ``elapsed * (factor - 1)``) — a degraded-but-alive *consumer*:
        it keeps heartbeating and acking rounds, but drains its exchange
        mailboxes slowly, so producers sending to it back up against the
        sender-side credit cap (``PATHWAY_EXCHANGE_CREDIT_BYTES``) and
        throttle instead of buffering without bound.  The slow-vs-dead
        distinction under test: this rank must be *backpressured*, never
        isolated."""
        if factor < 1.0:
            raise ValueError(f"factor must be >= 1.0, got {factor}")
        from pathway_tpu.engine.scheduler import Scheduler

        orig = Scheduler.run_epoch
        key = self._counter_key(Scheduler, "run_epoch")

        @functools.wraps(orig)
        def wrapper(sched: Any, time: int, inject: Any, **kwargs: Any) -> Any:
            self._bump(key)
            ctx = kwargs.get("ctx") or sched.ctx
            if getattr(ctx, "worker_id", 0) != rank:
                return orig(sched, time, inject, **kwargs)
            t0 = _time.monotonic()
            try:
                return orig(sched, time, inject, **kwargs)
            finally:
                _time.sleep((_time.monotonic() - t0) * (factor - 1.0))

        self._patch(Scheduler, "run_epoch", wrapper)


class _ResumeOnRestore:
    """Adapter so a pending SIGCONT rides chaos's patch-restore list: the
    restore loop calls ``setattr(owner, "noop", None)`` which lands in
    ``__setattr__`` below and fires the resume."""

    def __init__(self, timer: threading.Timer, resume: Callable[[], None]):
        object.__setattr__(self, "_timer", timer)
        object.__setattr__(self, "_resume", resume)

    def __setattr__(self, name: str, value: Any) -> None:
        object.__getattribute__(self, "_timer").cancel()
        object.__getattribute__(self, "_resume")()


_DRILL_PROGRAM = """
import os, sys
sys.path.insert(0, {repo!r})
import pathway_tpu as pw
from pathway_tpu.persistence import Backend, Config, PersistenceMode

_kill_rank = int(os.environ.get("CHAOS_KILL_RANK", "-1"))
if _kill_rank >= 0:
    from pathway_tpu.testing.chaos import chaos as _chaos

    _c = _chaos(seed=int(os.environ.get("CHAOS_SEED", "0")))
    _c.__enter__()  # never restored: this process dies or exits
    _c.kill_worker(_kill_rank, int(os.environ["CHAOS_KILL_EPOCH"]), hard=True)


class S(pw.Schema):
    word: str


t = pw.io.jsonlines.read({input!r}, schema=S, mode="static")
counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
pw.io.jsonlines.write(counts, {output!r})
pconf = Config.simple_config(
    Backend.filesystem({persist!r}),
    persistence_mode=PersistenceMode("operator_persisting"),
)
pw.run(
    autocommit_duration_ms=20,
    persistence_config=pconf,
    monitoring_level="none",
)
"""


class ClusterDrill:
    """Seedable end-to-end cluster fault drill.

    Runs one wordcount pipeline twice over the same generated corpus: a
    fault-free baseline, then a drill where a seeded-random worker is
    hard-killed (``os._exit``) at a seeded-random epoch while the cluster
    runs under :class:`~pathway_tpu.internals.resilience.ClusterSupervisor`
    with coordinated checkpointing enabled.  The drill passes when the
    recovered sink output is *byte-identical* to the fault-free run after
    canonicalization — the diff log is consolidated to final counts and
    serialized deterministically, because the raw log's row batching is
    timing-dependent even between two fault-free runs (what the
    consistency guarantee covers is the *content*, not the arbitrary
    interleaving).

    Small epochs (``PATHWAY_EPOCH_MAX_ROWS``) and a short checkpoint
    interval make static input produce many epochs and several
    checkpoints before the kill, so recovery genuinely exercises
    rollback + replay + sink-watermark truncation rather than a trivial
    from-scratch rerun.
    """

    def __init__(
        self,
        workdir: Any,
        *,
        seed: int = 0,
        processes: int = 2,
        threads: int = 1,
        rows: int = 400,
        vocab: int = 7,
        kill_rank: int | None = None,
        kill_epoch: int | None = None,
        checkpoint_interval_s: float = 0.05,
        epoch_max_rows: int | None = None,
        heartbeat_s: float = 0.2,
        liveness_timeout_s: float = 2.0,
        max_restarts: int = 3,
        timeout_s: float = 180.0,
        trace: bool = False,
    ) -> None:
        self.workdir = str(workdir)
        #: when set, the drill run spools flight-recorder dumps per rank
        #: (PATHWAY_TRACE_DIR) and merges them into one Chrome-trace file
        #: — the killed rank's spans survive via the pre-os._exit flush
        self.trace = bool(trace)
        self.seed = seed
        self.rng = random.Random(seed)
        self.processes = processes
        self.threads = threads
        self.rows = rows
        self.vocab = vocab
        n_ranks = processes * threads
        self.kill_rank = (
            kill_rank if kill_rank is not None else self.rng.randrange(n_ranks)
        )
        self.kill_epoch = (
            kill_epoch if kill_epoch is not None else self.rng.randrange(3, 7)
        )
        self.checkpoint_interval_s = checkpoint_interval_s
        # default epoch cap scales with the worker count: the corpus is
        # partitioned across ranks, and every rank must cut enough data
        # epochs (~10) that any kill_epoch drawn above can actually fire
        self.epoch_max_rows = (
            epoch_max_rows
            if epoch_max_rows is not None
            else max(1, rows // (n_ranks * 10))
        )
        self.heartbeat_s = heartbeat_s
        self.liveness_timeout_s = liveness_timeout_s
        self.max_restarts = max_restarts
        self.timeout_s = timeout_s

    # -- pieces ---------------------------------------------------------
    def _write_corpus(self) -> str:
        path = os.path.join(self.workdir, "corpus.jsonl")
        import json

        with open(path, "w") as f:
            for _ in range(self.rows):
                w = f"w{self.rng.randrange(self.vocab)}"
                f.write(json.dumps({"word": w}) + "\n")
        return path

    def _write_program(self, tag: str, input_path: str) -> tuple[str, str]:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        out = os.path.join(self.workdir, f"{tag}_out.jsonl")
        persist = os.path.join(self.workdir, f"{tag}_snap")
        prog = os.path.join(self.workdir, f"{tag}_prog.py")
        with open(prog, "w") as f:
            f.write(
                _DRILL_PROGRAM.format(
                    repo=repo, input=input_path, output=out, persist=persist
                )
            )
        return prog, out

    def _run_supervised(self, prog: str, extra_env: dict[str, str]) -> Any:
        import sys

        from pathway_tpu.internals.resilience import (
            ClusterSupervisor,
            ConnectorRecoveryPolicy,
        )

        env = {
            "PATHWAY_CHECKPOINT_INTERVAL": str(self.checkpoint_interval_s),
            "PATHWAY_EPOCH_MAX_ROWS": str(self.epoch_max_rows),
            "PATHWAY_CLUSTER_HEARTBEAT_S": str(self.heartbeat_s),
            "PATHWAY_CLUSTER_LIVENESS_TIMEOUT_S": str(self.liveness_timeout_s),
            **extra_env,
        }
        sup = ClusterSupervisor(
            [sys.executable, prog],
            self.processes,
            threads=self.threads,
            env=env,
            policy=ConnectorRecoveryPolicy(
                max_restarts=self.max_restarts,
                initial_delay_ms=10,
                jitter_ms=0,
                seed=self.seed,
            ),
            log_dir=self.workdir,
        )
        return sup.run(timeout=self.timeout_s)

    def _trace_env(self) -> dict[str, str]:
        """Env for a traced drill run: every rank (and every respawned
        generation) spools flight-recorder dumps into one directory."""
        if not self.trace:
            return {}
        return {"PATHWAY_TRACE_DIR": os.path.join(self.workdir, "trace")}

    def _merge_trace(self) -> tuple[Any, list[int]]:
        """Merge the per-rank spool into one Chrome-trace file; returns
        ``(path_or_None, sorted ranks that contributed spans)``."""
        if not self.trace:
            return None, []
        from pathway_tpu.internals import tracing as _tracing

        trace_file = _tracing.merge_trace_dir(
            os.path.join(self.workdir, "trace")
        )
        if trace_file is None:
            return None, []
        import json

        with open(trace_file) as f:
            events = json.load(f).get("traceEvents", [])
        return trace_file, sorted({int(e.get("pid", 0)) for e in events})

    @staticmethod
    def canonical_output(path: str) -> bytes:
        """Consolidate a jsonlines diff log to its final state and
        serialize deterministically (sorted keys) for byte comparison."""
        import json

        state: dict = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    key = row["word"]
                    if row["diff"] > 0:
                        state[key] = row["n"]
                    elif state.get(key) == row["n"]:
                        del state[key]
        return json.dumps(state, sort_keys=True).encode()

    # -- the drill ------------------------------------------------------
    def run(self) -> dict[str, Any]:
        corpus = self._write_corpus()

        prog, baseline_out = self._write_program("baseline", corpus)
        t0 = _time.monotonic()
        base_report = self._run_supervised(prog, {})
        baseline_seconds = _time.monotonic() - t0
        if base_report.returncode != 0:
            raise ChaosError(
                f"baseline cluster run failed: {base_report.failures}"
            )

        prog, drill_out = self._write_program("drill", corpus)
        drill_env = {
            "CHAOS_KILL_RANK": str(self.kill_rank),
            "CHAOS_KILL_EPOCH": str(self.kill_epoch),
            "CHAOS_SEED": str(self.seed),
        }
        drill_env.update(self._trace_env())
        t0 = _time.monotonic()
        drill_report = self._run_supervised(prog, drill_env)
        faulted_seconds = _time.monotonic() - t0
        trace_file, trace_ranks = self._merge_trace()

        baseline = self.canonical_output(baseline_out)
        recovered = self.canonical_output(drill_out)
        return {
            "ok": drill_report.returncode == 0 and baseline == recovered,
            "trace_file": trace_file,
            "trace_ranks": trace_ranks,
            "identical": baseline == recovered,
            "returncode": drill_report.returncode,
            "kill_rank": self.kill_rank,
            "kill_epoch": self.kill_epoch,
            "restarts": drill_report.restarts,
            "recovery_seconds": list(drill_report.recovery_seconds),
            "baseline_seconds": baseline_seconds,
            "faulted_seconds": faulted_seconds,
            "baseline_output": baseline.decode(),
            "recovered_output": recovered.decode(),
            "failures": list(drill_report.failures),
        }


_INDEX_DRILL_PROGRAM = """
import json, os, sys
sys.path.insert(0, {repo!r})
import pathway_tpu as pw
from pathway_tpu.persistence import Backend, Config, PersistenceMode

_kill_rank = int(os.environ.get("CHAOS_KILL_RANK", "-1"))
if _kill_rank >= 0:
    from pathway_tpu.testing.chaos import chaos as _chaos

    _c = _chaos(seed=int(os.environ.get("CHAOS_SEED", "0")))
    _c.__enter__()  # never restored: this process dies or exits
    _c.kill_worker_mid_merge(
        _kill_rank, on_nth_merge=int(os.environ["CHAOS_KILL_MERGE"])
    )


class Doc(pw.Schema):
    # "id" is the engine's reserved row-key column — the doc key is "doc"
    doc: str = pw.column_definition(primary_key=True)
    vec: str


class Q(pw.Schema):
    qid: str = pw.column_definition(primary_key=True)
    qvec: str


class DocSubject(pw.io.python.ConnectorSubject):
    # one ordered reader (worker 0): an upsert stream is ordered per key,
    # and the partitioned static-file byte-range split would let a
    # re-upsert race its own base version across ranks
    deterministic_replay = True  # same file, same order, every generation

    def run(self):
        n = 0
        with open({docs!r}) as f:
            for line in f:
                if not line.strip():
                    continue
                row = json.loads(line)
                self.next(doc=row["doc"], vec=row["vec"])
                n += 1
                if n % {commit_every} == 0:
                    self.commit()


docs = pw.io.python.read(DocSubject(), schema=Doc)
docs = docs.select(
    doc=pw.this.doc,
    vec=pw.apply(lambda s: tuple(json.loads(s)), pw.this.vec),
)
queries = pw.io.jsonlines.read({queries!r}, schema=Q, mode="static")
queries = queries.select(
    qid=pw.this.qid,
    qvec=pw.apply(lambda s: tuple(json.loads(s)), pw.this.qvec),
)

from pathway_tpu.stdlib.indexing import DataIndex
from pathway_tpu.stdlib.indexing.data_index import UsearchKnn

inner = UsearchKnn(
    docs.vec, dimensions={dim}, reserved_space=4096, delta_cap={delta_cap}
)
di = DataIndex(docs, inner)
reply = di.query(queries.qvec, number_of_matches={k})
out = reply.select(
    qid=pw.this.qid,
    ids=pw.apply(
        lambda ds: [d["doc"] for d in ds if d], pw.this._pw_index_reply
    ),
)
pw.io.jsonlines.write(out, {output!r})
pconf = Config.simple_config(
    Backend.filesystem({persist!r}),
    persistence_mode=PersistenceMode("operator_persisting"),
)
pw.run(
    autocommit_duration_ms=20,
    persistence_config=pconf,
    monitoring_level="none",
)
if int(os.environ.get("PATHWAY_PROCESS_ID", "0")) == 0:
    with open({dump!r}, "w") as f:
        json.dump(reply._node.adapter.stats(), f)
"""


class IndexDrill(ClusterDrill):
    """Live-index churn drill: exactly-once recovery from a crash
    mid-merge.

    Runs a doc-upsert + KNN-query pipeline twice over one seeded corpus
    (base docs followed by re-upserts of random ids under new vectors,
    flowing through the delta segment of a
    :class:`~pathway_tpu.stdlib.indexing.segments.SegmentedIndex`):
    a fault-free baseline, then a drill where the process hosting
    worker 0 — the index owner — is hard-killed between a finished
    background merge and its atomic commit
    (:meth:`chaos.kill_worker_mid_merge`).  The supervisor restarts the
    generation, the worker restores the checkpointed index (pre-merge
    view) and replays only the connector tail; primary-keyed rows make
    the replayed upserts idempotent.

    Passes when the recovered index holds each doc **exactly once**
    (index size equals the distinct id count — nothing dropped by the
    lost merge, nothing double-applied by the replay) and the final
    query answers reach ``recall_target`` against brute force over the
    final (post-churn) corpus.  ``delta_cap`` stays above the per-epoch
    batch size so churn actually flows through the delta segment and
    background merges fire; ``kill_merge=2`` leaves merge #1 and some
    checkpoints behind so recovery genuinely restores state.
    """

    def __init__(
        self,
        workdir: Any,
        *,
        seed: int = 0,
        processes: int = 2,
        n_docs: int = 64,
        n_upserts: int = 96,
        dim: int = 16,
        n_queries: int = 16,
        k: int = 5,
        delta_cap: int = 24,
        kill_merge: int = 2,
        recall_target: float = 0.95,
        **kwargs: Any,
    ) -> None:
        kwargs.setdefault("checkpoint_interval_s", 0.05)
        kwargs.setdefault("epoch_max_rows", 8)
        # the index lives on worker 0 (route_all_to_zero): kill that rank
        super().__init__(
            workdir,
            seed=seed,
            processes=processes,
            kill_rank=0,
            kill_epoch=1,
            **kwargs,
        )
        self.n_docs = n_docs
        self.n_upserts = n_upserts
        self.dim = dim
        self.n_queries = n_queries
        self.k = k
        self.delta_cap = delta_cap
        self.kill_merge = kill_merge
        self.recall_target = recall_target
        self._final: dict[str, list[float]] = {}
        self._queries: dict[str, list[float]] = {}

    # -- pieces ---------------------------------------------------------
    def _write_inputs(self) -> tuple[str, str]:
        import json

        import numpy as np

        rng = np.random.default_rng(self.seed)

        def vec() -> list[float]:
            v = rng.standard_normal(self.dim)
            return (v / np.linalg.norm(v)).tolist()

        lines = []
        for i in range(self.n_docs):
            v = vec()
            self._final[f"d{i}"] = v
            lines.append({"doc": f"d{i}", "vec": json.dumps(v)})
        for _ in range(self.n_upserts):
            doc_id = f"d{int(rng.integers(self.n_docs))}"
            v = vec()
            self._final[doc_id] = v
            lines.append({"doc": doc_id, "vec": json.dumps(v)})
        docs_path = os.path.join(self.workdir, "docs.jsonl")
        with open(docs_path, "w") as f:
            for row in lines:
                f.write(json.dumps(row) + "\n")
        queries_path = os.path.join(self.workdir, "queries.jsonl")
        with open(queries_path, "w") as f:
            for j in range(self.n_queries):
                v = vec()
                self._queries[f"q{j}"] = v
                f.write(json.dumps({"qid": f"q{j}", "qvec": json.dumps(v)}) + "\n")
        return docs_path, queries_path

    def _write_index_program(
        self, tag: str, docs_path: str, queries_path: str
    ) -> tuple[str, str, str]:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        out = os.path.join(self.workdir, f"{tag}_out.jsonl")
        dump = os.path.join(self.workdir, f"{tag}_index.json")
        persist = os.path.join(self.workdir, f"{tag}_snap")
        prog = os.path.join(self.workdir, f"{tag}_prog.py")
        with open(prog, "w") as f:
            f.write(
                _INDEX_DRILL_PROGRAM.format(
                    repo=repo,
                    docs=docs_path,
                    queries=queries_path,
                    output=out,
                    persist=persist,
                    dump=dump,
                    dim=self.dim,
                    delta_cap=self.delta_cap,
                    k=self.k,
                    commit_every=self.epoch_max_rows,
                )
            )
        return prog, out, dump

    def _final_answers(self, path: str) -> dict[str, list]:
        """Consolidate the query sink's diff log to its final state."""
        import json

        state: dict[str, list] = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    if row["diff"] > 0:
                        state[row["qid"]] = row["ids"]
                    elif state.get(row["qid"]) == row["ids"]:
                        del state[row["qid"]]
        return state

    def _recall(self, output_path: str) -> float:
        """Top-k recall of the sink's final answers vs brute force over
        the final (post-churn) corpus."""
        import numpy as np

        answers = self._final_answers(output_path)
        ids = sorted(self._final)
        mat = np.asarray([self._final[i] for i in ids], np.float64)
        k = min(self.k, len(ids))
        hits, total = 0, 0
        for qid, qv in self._queries.items():
            scores = mat @ np.asarray(qv, np.float64)
            gt = {ids[i] for i in np.argsort(-scores)[:k]}
            hits += len(gt & set(answers.get(qid, ())))
            total += k
        return hits / max(total, 1)

    # -- the drill ------------------------------------------------------
    def run(self) -> dict[str, Any]:
        docs_path, queries_path = self._write_inputs()

        prog, base_out, base_dump = self._write_index_program(
            "baseline", docs_path, queries_path
        )
        base_report = self._run_supervised(prog, {})
        if base_report.returncode != 0:
            raise ChaosError(
                f"baseline index run failed: {base_report.failures}"
            )

        prog, drill_out, drill_dump = self._write_index_program(
            "drill", docs_path, queries_path
        )
        t0 = _time.monotonic()
        drill_report = self._run_supervised(
            prog,
            {
                "CHAOS_KILL_RANK": str(self.kill_rank),
                "CHAOS_KILL_MERGE": str(self.kill_merge),
                "CHAOS_SEED": str(self.seed),
                **self._trace_env(),
            },
        )
        faulted_seconds = _time.monotonic() - t0
        trace_file, trace_ranks = self._merge_trace()

        import json

        def read_dump(path: str) -> dict:
            if not os.path.exists(path):
                return {}
            with open(path) as f:
                return json.load(f)

        expected = len(self._final)
        base_stats = read_dump(base_dump)
        drill_stats = read_dump(drill_dump)
        baseline_recall = self._recall(base_out)
        recall = self._recall(drill_out)
        exactly_once = drill_stats.get("size") == expected
        return {
            "ok": (
                drill_report.returncode == 0
                and exactly_once
                and recall >= self.recall_target
            ),
            "exactly_once": exactly_once,
            "expected_size": expected,
            "recovered_size": drill_stats.get("size"),
            "baseline_size": base_stats.get("size"),
            "recall": recall,
            "baseline_recall": baseline_recall,
            "merges_total": drill_stats.get("merges_total", 0),
            "baseline_merges_total": base_stats.get("merges_total", 0),
            "restarts": drill_report.restarts,
            "recovery_seconds": list(drill_report.recovery_seconds),
            "faulted_seconds": faulted_seconds,
            "returncode": drill_report.returncode,
            "failures": list(drill_report.failures),
            "trace_file": trace_file,
            "trace_ranks": trace_ranks,
        }


def flaky_once(
    items: Iterable[Any],
    fail_before_index: int,
    exc_factory: Callable[[], BaseException] | None = None,
) -> Callable[[], Iterable[Any]]:
    """Generator factory for a transiently-faulty source: the FIRST pass
    raises just before yielding item ``fail_before_index``; every later
    pass yields all items.  Pairs with a deterministic-replay reader +
    :class:`~pathway_tpu.internals.resilience.ConnectorRecoveryPolicy`
    to drill restart-with-resume (each row delivered exactly once)."""
    items = list(items)
    state = {"tripped": False}
    make_exc = exc_factory or (
        lambda: ChaosError(f"injected source fault before row {fail_before_index}")
    )

    def gen() -> Iterable[Any]:
        for i, item in enumerate(items):
            if not state["tripped"] and i == fail_before_index:
                state["tripped"] = True
                raise make_exc()
            yield item

    return gen
