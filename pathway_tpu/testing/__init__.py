"""Test-support utilities shipped with the package.

``pathway_tpu.testing.chaos`` is the deterministic fault-injection
harness used by the crash-recovery drills (and usable against user
pipelines: inject connector faults, torn persistence writes, and
crash-between-snapshot-and-commit scenarios under a fixed seed).
"""

from pathway_tpu.testing.chaos import ChaosError, chaos, flaky_once

__all__ = ["ChaosError", "chaos", "flaky_once"]
