"""Shape bucketing: bound XLA recompilation under dynamic batch sizes.

Streaming epochs produce arbitrary batch sizes; XLA compiles one program
per static shape.  Rounding every dynamic dimension up to a power of two
(with a floor) keeps the number of compiled variants logarithmic — the
TPU-side equivalent of the reference's 2x index growth policy
(``src/external_integration/brute_force_knn_integration.rs:115-119``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["bucket_size", "pad_rows", "pad_dim"]


def bucket_size(n: int, min_bucket: int = 8, max_bucket: int | None = None) -> int:
    """Smallest power of two >= n (and >= min_bucket), optionally clamped."""
    if n <= 0:
        return min_bucket
    b = max(min_bucket, 1 << (int(n - 1).bit_length()))
    if max_bucket is not None:
        b = min(b, max_bucket)
    return max(b, n) if max_bucket is None else b


def pad_rows(arr: np.ndarray, bucket: int, fill: float | int = 0) -> np.ndarray:
    """Pad axis 0 of ``arr`` up to ``bucket`` rows with ``fill``."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    pad = np.full((bucket - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def pad_dim(arr: np.ndarray, axis: int, size: int, fill: float | int = 0) -> np.ndarray:
    """Pad ``axis`` of ``arr`` up to ``size`` with ``fill``."""
    n = arr.shape[axis]
    if n == size:
        return arr
    shape = list(arr.shape)
    shape[axis] = size - n
    pad = np.full(shape, fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=axis)
