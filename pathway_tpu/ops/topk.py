"""Masked top-k over score matrices."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["masked_top_k"]

# Python float, NOT a jnp device array: a device-resident constant baked
# into jitted closures forces a host<->device round trip on EVERY call on
# remote/tunneled backends (~70-90 ms each — measured; it masqueraded as
# "link RTT" in earlier benchmarks).
NEG_INF = -3.0e38


def masked_top_k(
    scores: jax.Array, valid: jax.Array | None, k: int
) -> tuple[jax.Array, jax.Array]:
    """Top-k column indices per row, ignoring columns where ``valid == 0``.

    scores [nq, n] (higher = better), valid [n] in {0,1} or None.
    Returns (values [nq, k], indices [nq, k]); masked-out slots surface
    as values <= NEG_INF/2 so callers can drop them.
    """
    s = scores.astype(jnp.float32)
    if valid is not None:
        s = jnp.where(valid.astype(bool)[None, :], s, NEG_INF)
    return jax.lax.top_k(s, k)
