"""``shard_map`` across jax versions.

Newer jax exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases only have ``jax.experimental.shard_map.shard_map`` whose
equivalent kwarg is ``check_rep``.  Callers use the new spelling and
this shim translates when running on the old API.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

__all__ = ["shard_map"]


def shard_map(
    f: Callable[..., Any],
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = True,
) -> Callable[..., Any]:
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
