"""Ring attention: sequence-parallel exact attention over a device mesh.

Long-context support (SURVEY.md §5): sequences too long for one device's
memory are sharded over the mesh ``"data"`` axis; each device holds a
Q/K/V block and K/V blocks rotate around the ring via ``ppermute`` over
ICI while a flash-attention-style running softmax (m, l, o accumulators)
keeps the computation exact.  Memory per device is O(L_local^2-free):
only the current K/V block is resident.

Non-causal (encoder) attention by default — the document-embedding
workload — with an optional key padding mask; causal masking composes
via the block position offsets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from pathway_tpu.ops.shard_map_compat import shard_map

__all__ = ["ring_attention", "local_attention"]

_NEG = -1e30


def local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Plain single-device attention. q/k/v: [B, L, H, D]; mask: [B, L]
    (key positions)."""
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    s = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = s + jnp.where(mask.astype(bool)[:, None, None, :], 0.0, _NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def _ring_body(q, k0, v0, mask0, axis_name: str, n_shards: int):
    """Runs on ONE device inside shard_map: q/k0/v0 are the local blocks."""
    b, l_local, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def step(carry, _):
        o, m, l, k_cur, v_cur, mask_cur = carry
        s = jnp.einsum("blhd,bmhd->bhlm", q, k_cur).astype(jnp.float32) * scale
        s = s + jnp.where(mask_cur.astype(bool)[:, None, None, :], 0.0, _NEG)
        m_blk = jnp.max(s, axis=-1)  # [b, h, l]
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhlm,bmhd->bhld", p, v_cur.astype(jnp.float32)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt, mask_nxt), None

    o0 = jnp.zeros((b, h, l_local, d), jnp.float32)
    m0 = jnp.full((b, h, l_local), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, l_local), jnp.float32)
    (o, m, l, _k, _v, _mk), _ = jax.lax.scan(
        step, (o0, m0, l0, k0, v0, mask0), None, length=n_shards
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b, l, h, d]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    mesh: Mesh,
    axis: str = "data",
) -> jax.Array:
    """Exact attention with the SEQUENCE dimension sharded over ``axis``.

    q/k/v: [B, L, H, D] global shapes (L divisible by the axis size);
    mask: [B, L] key validity.  Returns [B, L, H, D] sharded like q.
    """
    n = mesh.shape[axis]
    if mask is None:
        mask = jnp.ones(q.shape[:2], jnp.int32)

    body = functools.partial(_ring_body, axis_name=axis, n_shards=n)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis),
        ),
        out_specs=P(None, axis, None, None),
        check_vma=False,
    )
    return mapped(q, k, v, mask)
