"""Distance/similarity kernels: matmul-shaped so XLA maps them to the MXU.

TPU re-design of the reference's scalar distance loops
(``src/external_integration/brute_force_knn_integration.rs:40-76``):
one ``[nq, d] @ [d, n]`` matmul computes every query-corpus pair at once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["normalize", "dot_scores", "cosine_scores", "l2sq_distances"]


def normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """L2-normalize rows (f32 accumulation even for bf16 inputs)."""
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True))
    return (x / jnp.maximum(norm, eps).astype(x.dtype)).astype(x.dtype)


def dot_scores(queries: jax.Array, corpus: jax.Array) -> jax.Array:
    """``[nq, d] x [n, d] -> [nq, n]`` inner-product scores (higher=closer)."""
    return jax.lax.dot_general(
        queries,
        corpus,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def cosine_scores(queries: jax.Array, corpus: jax.Array) -> jax.Array:
    """Cosine similarity, normalizing both sides."""
    return dot_scores(normalize(queries), normalize(corpus))


def l2sq_distances(queries: jax.Array, corpus: jax.Array) -> jax.Array:
    """Squared L2 distance via the ||q||^2 - 2qc + ||c||^2 expansion
    (keeps the O(nq*n*d) term on the MXU; lower=closer)."""
    q32 = queries.astype(jnp.float32)
    c32 = corpus.astype(jnp.float32)
    qq = jnp.sum(q32 * q32, axis=-1, keepdims=True)  # [nq, 1]
    cc = jnp.sum(c32 * c32, axis=-1)  # [n]
    qc = dot_scores(queries, corpus)  # [nq, n]
    return jnp.maximum(qq - 2.0 * qc + cc[None, :], 0.0)
