"""Mask-aware sequence pooling for sentence encoders."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["masked_mean_pool", "cls_pool"]


def masked_mean_pool(hidden: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean over valid positions. hidden [B, L, H], mask [B, L] {0,1}."""
    m = mask.astype(jnp.float32)[..., None]
    summed = jnp.sum(hidden.astype(jnp.float32) * m, axis=1)
    counts = jnp.maximum(jnp.sum(m, axis=1), 1.0)
    return (summed / counts).astype(hidden.dtype)


def cls_pool(hidden: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """First-token ([CLS]) pooling."""
    return hidden[:, 0, :]
