"""TPU compute ops: the jitted numeric plane primitives.

These are the building blocks the reference implements as Rust loops
(e.g. brute-force KNN distance loops,
``src/external_integration/brute_force_knn_integration.rs:22-120``) —
re-designed as XLA-friendly batched array ops: matmul-based distances on
the MXU, masked top-k, mask-aware pooling, and shape bucketing to bound
recompilation under live streaming input.
"""

from pathway_tpu.ops.bucketing import bucket_size, pad_dim, pad_rows
from pathway_tpu.ops.distances import (
    cosine_scores,
    dot_scores,
    l2sq_distances,
    normalize,
)
from pathway_tpu.ops.pooling import cls_pool, masked_mean_pool
from pathway_tpu.ops.topk import masked_top_k

__all__ = [
    "bucket_size",
    "pad_dim",
    "pad_rows",
    "cosine_scores",
    "dot_scores",
    "l2sq_distances",
    "normalize",
    "masked_mean_pool",
    "cls_pool",
    "masked_top_k",
]
