"""``pw.debug`` — build tables from literals, run & print results.

Capability parity with reference ``python/pathway/debug/__init__.py``:
``table_from_markdown`` (``:312``), ``table_from_rows``, ``table_from_pandas``,
``compute_and_print`` (``:207``), ``compute_and_print_update_stream``
(``:235``), ``table_to_pandas``, ``StreamGenerator`` (``:496``).
"""

from __future__ import annotations

import re
import threading
from typing import Any, Iterable, Mapping

from pathway_tpu.engine import graph as eg
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _parse_cell(text: str) -> Any:
    text = text.strip()
    if text in ("", "None"):
        return None
    if text == "True":
        return True
    if text == "False":
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    if len(text) >= 2 and text[0] == text[-1] and text[0] in "\"'":
        return text[1:-1]
    return text


def table_from_markdown(
    txt: str,
    *,
    id_from: list[str] | None = None,
    schema: Any = None,
    _stream: bool = False,
    **kwargs: Any,
) -> Table:
    """Parse a markdown/ascii table into a static table.  A column named
    ``id`` gives explicit row keys; ``__time__``/``__diff__`` columns build
    an update stream (reference ``debug/__init__.py:312-481``)."""
    lines = [l for l in txt.strip().splitlines() if l.strip() and not set(l.strip()) <= {"-", "|", "+", " "}]

    # outer-pipe style ("| a | b |") is decided by the HEADER: in the
    # bare style ("a | b") a row's leading pipe marks an EMPTY FIRST
    # CELL ("  | n1" is [None, "n1"]), which a blanket strip("|") used
    # to swallow
    outer_pipes = lines[0].strip().startswith("|") if lines else False

    def split_line(line: str) -> list[str]:
        stripped = line.strip()
        if "|" in stripped:
            parts = stripped.split("|")
            if outer_pipes:
                if stripped.startswith("|"):
                    parts = parts[1:]
                if stripped.endswith("|"):
                    parts = parts[:-1]
            # bare style keeps every field: a trailing empty cell parses
            # to None exactly where header-length padding would put it
            return [c.strip() for c in parts]
        # whitespace-separated; quoted strings stay whole
        return re.findall(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"|\S+", line)

    header = [h for h in split_line(lines[0]) if h]
    rows: list[list[Any]] = []
    for line in lines[1:]:
        cells = [c for c in split_line(line)]
        row = [_parse_cell(c) for c in cells[: len(header)]]
        row.extend([None] * (len(header) - len(row)))  # trailing empty cells
        rows.append(row)

    has_id = "id" in header
    special = [c for c in ("__time__", "__diff__") if c in header]
    data_cols = [c for c in header if c != "id" and c not in special]

    if special:
        return _stream_table_from_rows(header, rows, data_cols, has_id, schema)

    out_rows: list[tuple[K.Pointer, tuple]] = []
    for i, r in enumerate(rows):
        vals = dict(zip(header, r))
        if has_id:
            key = K.ref_scalar(vals["id"])
        elif id_from:
            key = K.ref_scalar(*[vals[c] for c in id_from])
        elif schema is not None and sch.is_schema(schema) and schema.primary_key_columns():
            key = K.ref_scalar(*[vals[c] for c in schema.primary_key_columns()])
        else:
            key = K.sequential_key(i)
        out_rows.append((key, tuple(vals[c] for c in data_cols)))

    dtypes = _infer_dtypes(data_cols, [v for _, v in out_rows], schema)
    node = eg.InputNode(
        G.engine_graph, n_cols=len(data_cols), static_rows=out_rows, name="markdown"
    )
    return Table(node, data_cols, dtypes, name="markdown")


def _infer_dtypes(cols: list[str], rows: list[tuple], schema: Any) -> dict[str, dt.DType]:
    if schema is not None and sch.is_schema(schema):
        return {c: schema.__columns__[c].dtype for c in cols if c in schema.__columns__}
    dtypes: dict[str, dt.DType] = {}
    for i, c in enumerate(cols):
        seen = {dt.dtype_of_value(r[i]) for r in rows if r[i] is not None}
        has_none = any(r[i] is None for r in rows)
        if len(seen) == 1:
            d = seen.pop()
        elif seen == {dt.INT, dt.FLOAT}:
            d = dt.FLOAT
        else:
            d = dt.ANY
        dtypes[c] = dt.Optional(d) if has_none and d != dt.ANY else d
    return dtypes


class _StreamClock:
    """Deterministic replay order for every markdown stream subject built
    on one graph.  Reader threads replay concurrently, so without
    coordination the epoch a row lands in depends on thread scheduling —
    two ``__time__`` tables only line up by luck.  The clock serializes
    the replay into one global schedule: every (time, subject) batch in
    ascending ``__time__`` order, registration (= construction) order
    within a time, each batch committed as its own epoch.  That is the
    interleaving the unsynchronized replay produced when the race went
    the expected way — now it is the only interleaving."""

    #: a reader that never starts (its node pruned from the run, or the
    #: run cancelled mid-replay) stalls the schedule; after this wait the
    #: remaining readers proceed unserialized rather than hang
    _STEP_TIMEOUT_S = 5.0

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._batches: list[tuple[int, int]] = []  # (time, subject id)
        self._n_subjects = 0
        self._steps: dict[tuple[int, int], int] | None = None
        self._counter = 0

    def register(self, times: Iterable[int]) -> int:
        """Called at graph-build time; returns the subject's id."""
        with self._cond:
            sid = self._n_subjects
            self._n_subjects += 1
            self._batches.extend((t, sid) for t in sorted(set(times)))
            return sid

    def reset(self) -> None:
        """Rewind for a fresh scheduler run: the same graph re-runs every
        subject from scratch, so the schedule replays from slot 0."""
        with self._cond:
            self._counter = 0
            self._steps = None  # pick up subjects registered since the freeze
            self._cond.notify_all()

    def _schedule(self) -> dict[tuple[int, int], int]:
        # first reader in freezes membership (graph construction is done
        # before the scheduler starts any reader thread)
        if self._steps is None:
            self._batches.sort()
            self._steps = {b: i for i, b in enumerate(self._batches)}
        return self._steps

    def step(self, t: int, sid: int, emit: Any) -> None:
        """Run ``emit`` (enqueue rows + commit) at this batch's slot in
        the global schedule."""
        with self._cond:
            # a subject built AFTER the first replay froze the schedule
            # (tables added to an already-run graph) has no slot: emit
            # unserialized rather than renumber a live schedule
            idx = self._schedule().get((t, sid))
            if idx is not None:
                self._cond.wait_for(
                    lambda: self._counter >= idx, timeout=self._STEP_TIMEOUT_S
                )
        try:
            emit()
        finally:
            if idx is not None:
                with self._cond:
                    self._counter = max(self._counter, idx + 1)
                    self._cond.notify_all()


class _StreamSubject:
    """Replays timed rows through the connector interface so ``__time__`` /
    ``__diff__`` markdown columns become a genuine update stream.  With a
    :class:`_StreamClock` every batch lands at its deterministic slot in
    the graph-wide replay schedule."""

    def __init__(
        self,
        timed_rows: list[tuple[int, K.Pointer, tuple, int]],
        clock: _StreamClock | None = None,
    ):
        self.timed_rows = sorted(timed_rows, key=lambda r: r[0])
        self.clock = clock
        self.sid = (
            clock.register({t for t, _k, _v, _d in self.timed_rows})
            if clock is not None
            else 0
        )

    def _emit(self, events: Any, batch: list) -> None:
        for key, vals, diff in batch:
            if diff >= 0:
                events.add(key, vals)
            else:
                events.remove(key, vals)
        events.commit()

    def run(self, events: Any) -> None:
        by_time: dict[int, list] = {}
        for t, key, vals, diff in self.timed_rows:
            by_time.setdefault(t, []).append((key, vals, diff))
        for t in sorted(by_time):
            if self.clock is not None:
                self.clock.step(
                    t, self.sid, lambda b=by_time[t]: self._emit(events, b)
                )
            else:
                self._emit(events, by_time[t])


def _occurrence_key(tag: str, row: tuple, diff: int, occupancy: dict) -> K.Pointer:
    """Value-derived stream keys with multiset semantics: the n-th
    outstanding addition of equal row values gets a distinct key, and a
    retraction targets the LATEST outstanding occurrence — so duplicates
    stay distinct rows AND ``__diff__=-1`` lines retract the row their
    matching ``+1`` line added (sequential per-line keys would miss)."""
    from pathway_tpu.engine.stream import hashable_row

    h = hashable_row(row)
    outstanding = occupancy.setdefault(h, [0, []])
    if diff >= 0:
        occ = outstanding[0]
        outstanding[0] += 1
        key = K.ref_scalar(tag, occ, *row)
        outstanding[1].append(key)
        return key
    if outstanding[1]:
        return outstanding[1].pop()
    return K.ref_scalar(tag, 0, *row)  # retract-before-add


def _stream_table_from_rows(
    header: list[str], rows: list[list[Any]], data_cols: list[str], has_id: bool, schema: Any
) -> Table:
    timed: list[tuple[int, K.Pointer, tuple, int]] = []
    occupancy: dict = {}
    for i, r in enumerate(rows):
        vals = dict(zip(header, r))
        t = int(vals.get("__time__") or 0)  # `or`: a padded None cell
        diff = int(vals.get("__diff__") or 1)
        row = tuple(vals[c] for c in data_cols)
        if has_id:
            key = K.ref_scalar(vals["id"])
        else:
            key = _occurrence_key("__md_stream__", row, diff, occupancy)
        timed.append((t, key, row, diff))
    dtypes = _infer_dtypes(data_cols, [v for _, _, v, _ in timed], schema)
    graph = G.engine_graph
    clock = getattr(graph, "_md_stream_clock", None)
    if clock is None:
        clock = graph._md_stream_clock = _StreamClock()
    node = eg.InputNode(
        graph,
        n_cols=len(data_cols),
        subject=_StreamSubject(timed, clock),
        name="markdown_stream",
    )
    return Table(node, data_cols, dtypes, name="markdown_stream")


def stream_table_from_markdown(txt: str, **kwargs: Any) -> Table:
    return table_from_markdown(txt, _stream=True, **kwargs)


def table_from_rows(
    schema: Any,
    rows: Iterable[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    cols = schema.column_names()
    pk = schema.primary_key_columns()
    out_rows: list[tuple[K.Pointer, tuple]] = []
    timed: list[tuple[int, K.Pointer, tuple, int]] = []
    occupancy: dict = {}
    for i, r in enumerate(rows):
        if is_stream:
            *vals, time_, diff = r
        else:
            vals = list(r)
            time_, diff = 0, 1
        if pk:
            key = K.ref_scalar(*[vals[cols.index(c)] for c in pk])
        elif is_stream:
            key = _occurrence_key("__rows_stream__", tuple(vals), diff, occupancy)
        else:
            key = K.sequential_key(i)
        if is_stream:
            timed.append((time_, key, tuple(vals), diff))
        else:
            out_rows.append((key, tuple(vals)))
    dtypes = {c: schema.__columns__[c].dtype for c in cols}
    if is_stream:
        node = eg.InputNode(
            G.engine_graph, n_cols=len(cols), subject=_StreamSubject(timed), name="rows_stream"
        )
    else:
        node = eg.InputNode(
            G.engine_graph, n_cols=len(cols), static_rows=out_rows, name="rows"
        )
    return Table(node, cols, dtypes, name="rows")


def table_from_dicts(rows: Iterable[Mapping[str, Any]], schema: Any = None) -> Table:
    rows = list(rows)
    if schema is None:
        cols: list[str] = []
        for r in rows:
            for c in r:
                if c not in cols:
                    cols.append(c)
        schema = sch.schema_from_types(**{c: Any for c in cols})
    return table_from_rows(schema, [tuple(r.get(c) for c in schema.column_names()) for r in rows])


def table_from_pandas(df: Any, id_from: list[str] | None = None, schema: Any = None) -> Table:
    if schema is None:
        schema = sch.schema_from_pandas(df, id_from=id_from)
    cols = schema.column_names()
    rows = [tuple(df.iloc[i][c] for c in cols) for i in range(len(df))]
    # normalise numpy scalars to python
    import numpy as np

    def norm(v: Any) -> Any:
        if isinstance(v, np.generic):
            return v.item()
        return v

    rows = [tuple(norm(v) for v in r) for r in rows]
    return table_from_rows(schema, rows)


def table_from_parquet(
    path: Any, id_from: list[str] | None = None, schema: Any = None
) -> Table:
    """Static table from a parquet file (reference
    ``debug/__init__.py:312-481`` table_from_parquet)."""
    import pandas as pd

    return table_from_pandas(pd.read_parquet(path), id_from=id_from, schema=schema)


def table_to_parquet(table: Table, filename: Any) -> None:
    """Run the graph and write the table's final rows to parquet."""
    table_to_pandas(table, include_id=False).to_parquet(filename)


def _run_capture(*tables: Table) -> list[tuple[dict, list]]:
    captures = [t._capture_node() for t in tables]
    clock = getattr(G.engine_graph, "_md_stream_clock", None)
    if clock is not None:
        clock.reset()
    sched = Scheduler(G.engine_graph)
    ctx = sched.run()
    G.last_run_ctx = ctx
    out = []
    for c in captures:
        st = ctx.state(c)
        out.append((st["rows"], st["stream"]))
    return out


def table_to_dicts(table: Table) -> tuple[list, dict[str, dict]]:
    (rows, _), = _run_capture(table)
    keys = list(rows.keys())
    cols = {
        c: {k: rows[k][i] for k in keys} for i, c in enumerate(table._column_names)
    }
    return keys, cols


def table_to_pandas(table: Table, include_id: bool = True) -> Any:
    import pandas as pd

    (rows, _), = _run_capture(table)
    data = {c: [v[i] for v in rows.values()] for i, c in enumerate(table._column_names)}
    if include_id:
        return pd.DataFrame(data, index=[repr(k) for k in rows.keys()])
    return pd.DataFrame(data)


def _fmt(v: Any) -> str:
    if v is None:
        return "None"
    if v is api.ERROR:
        return "Error"
    return repr(v) if isinstance(v, str) else str(v)


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    squash_updates: bool = True,
    **kwargs: Any,
) -> None:
    """Run the graph; print the final state of ``table``."""
    (rows, _), = _run_capture(table)
    cols = table._column_names
    header = (["id"] if include_id else []) + list(cols)
    lines = []
    sortable = sorted(
        rows.items(), key=lambda kv: tuple(repr(v) for v in kv[1])
    )
    for key, vals in sortable[: n_rows if n_rows is not None else len(sortable)]:
        row = ([repr(key)] if include_id else []) + [_fmt(v) for v in vals]
        lines.append(row)
    widths = [max(len(h), *(len(l[i]) for l in lines)) if lines else len(h) for i, h in enumerate(header)]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip())
    for l in lines:
        print(" | ".join(c.ljust(w) for c, w in zip(l, widths)).rstrip())


def compute_and_print_update_stream(
    table: Table, *, include_id: bool = True, **kwargs: Any
) -> None:
    """Run the graph; print every (time, diff) update of ``table``."""
    (_, stream), = _run_capture(table)
    cols = table._column_names
    header = (["id"] if include_id else []) + list(cols) + ["__time__", "__diff__"]
    lines = []
    for key, vals, time, diff in stream:
        row = ([repr(key)] if include_id else []) + [_fmt(v) for v in vals] + [str(time), str(diff)]
        lines.append(row)
    widths = [max(len(h), *(len(l[i]) for l in lines)) if lines else len(h) for i, h in enumerate(header)]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip())
    for l in lines:
        print(" | ".join(c.ljust(w) for c, w in zip(l, widths)).rstrip())


class StreamGenerator:
    """Programmatic update-stream builder for tests (reference
    ``debug/__init__.py:496``)."""

    def __init__(self) -> None:
        self._events: list[tuple[int, K.Pointer, tuple, int]] = []
        self._counter = 0

    def table(self, schema: Any, batches: list[dict[K.Pointer, list]] | None = None) -> Table:
        cols = schema.column_names()
        node = eg.InputNode(
            G.engine_graph,
            n_cols=len(cols),
            subject=_StreamSubject(self._events),
            name="stream_generator",
        )
        dtypes = {c: schema.__columns__[c].dtype for c in cols}
        return Table(node, cols, dtypes, name="stream_generator")

    def _next_key(self) -> K.Pointer:
        self._counter += 1
        return K.sequential_key(self._counter)

    def add(self, time: int, values: tuple, key: K.Pointer | None = None, diff: int = 1) -> K.Pointer:
        key = key if key is not None else self._next_key()
        self._events.append((time, key, values, diff))
        return key

    def table_from_list_of_batches_by_workers(self, *args: Any, **kwargs: Any) -> Table:
        raise NotImplementedError("multi-worker stream generation: single-worker build")
