"""``pw.graphs`` — graph algorithms over streaming edge tables
(reference ``python/pathway/stdlib/graphs/``: ``graph.py:77,121``,
``bellman_ford/impl.py``, ``pagerank/impl.py``,
``louvain_communities/impl.py``).  All incremental via ``pw.iterate``."""

from __future__ import annotations

import dataclasses
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = [
    "Graph",
    "WeightedGraph",
    "bellman_ford",
    "pagerank",
    "louvain_level",
    "louvain_communities",
]


@dataclasses.dataclass
class Graph:
    """Edges table with columns u, v (reference ``graphs/graph.py:77``)."""

    edges: Table

    def without_self_loops(self) -> "Graph":
        return Graph(self.edges.filter(pw.this.u != pw.this.v))


@dataclasses.dataclass
class WeightedGraph(Graph):
    """Edges carry a ``weight`` column (reference ``graph.py:121``)."""

    @classmethod
    def from_edges(cls, edges: Table, weight: Any = None) -> "WeightedGraph":
        if weight is not None and getattr(weight, "_name", "weight") != "weight":
            edges = edges.select(u=pw.this.u, v=pw.this.v, weight=weight)
        return cls(edges)


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Single-source shortest paths (reference
    ``graphs/bellman_ford/impl.py``): ``vertices`` has a ``dist`` column
    (0 for sources, None/inf otherwise); ``edges`` has u, v, dist."""
    import math

    INF = math.inf

    start = vertices.select(
        dist=pw.apply(lambda d: INF if d is None else float(d), pw.this.dist)
    )

    def body(state: Table, edges: Table) -> Table:
        # candidate distances: via each incoming edge
        relaxed = edges.join(state, pw.left.u == pw.right.id).select(
            v=pw.left.v,
            cand=pw.apply(
                lambda du, w: du + float(w), pw.right.dist, pw.left.dist
            ),
        )
        best = relaxed.groupby(relaxed.v, id=relaxed.v).reduce(
            cand=pw.reducers.min(relaxed.cand)
        )
        improved = state.join_left(
            best, pw.left.id == pw.right.id, id=pw.left.id
        ).select(
            dist=pw.apply(
                lambda d, c: d if c is None else min(d, c),
                pw.left.dist,
                pw.right.cand,
            ),
        )
        return improved

    # join on vertex ids: state is keyed by vertex key; edges are
    # read-only context inside the fixpoint
    return pw.iterate(body, state=start, edges=edges)


def pagerank(edges: Table, steps: int = 5, damping: float = 0.85) -> Table:
    """PageRank over an edge table u->v (reference
    ``graphs/pagerank/impl.py``; integer arithmetic there, floats here)."""
    vertices = (
        edges.select(w=pw.this.u)
        .concat_reindex(edges.select(w=pw.this.v))
        .groupby(pw.this.w)
        .reduce(w=pw.this.w)
    )
    degrees = edges.groupby(edges.u).reduce(u=edges.u, deg=pw.reducers.count())
    ranks = vertices.select(w=pw.this.w, rank=pw.apply(lambda _w: 1.0, pw.this.w))

    for _ in range(steps):
        contrib = (
            edges.join(ranks, pw.left.u == pw.right.w)
            .select(v=pw.left.v, part=pw.right.rank, u=pw.left.u)
            .join(degrees, pw.left.u == pw.right.u)
            .select(
                v=pw.left.v,
                part=pw.apply(lambda r, d: r / d, pw.left.part, pw.right.deg),
            )
        )
        summed = contrib.groupby(contrib.v).reduce(
            v=contrib.v, total=pw.reducers.sum(contrib.part)
        )
        ranks = vertices.join_left(
            summed, pw.left.w == pw.right.v, id=pw.left.id
        ).select(
            w=pw.left.w,
            rank=pw.apply(
                lambda t, d=damping: (1 - d) + d * (t or 0.0), pw.right.total
            ),
        )
    return ranks


def louvain_level(G: WeightedGraph, iterations: int = 10) -> Table:
    """One level of Louvain community detection (reference
    ``louvain_communities/impl.py``, simplified single-level greedy pass):
    returns a table keyed by vertex with a ``community`` column."""
    edges = G.edges
    vertices = (
        edges.select(w=pw.this.u)
        .concat_reindex(edges.select(w=pw.this.v))
        .groupby(pw.this.w, id=pw.this.w)
        .reduce(w=pw.this.w)
    )
    comm0 = vertices.select(node=pw.this.w, community=pw.this.w)

    # host-side greedy modularity pass over the (small) aggregated edge set
    packed_edges = edges.reduce(
        all_edges=pw.reducers.tuple(
            pw.apply(lambda u, v, w: (u, v, float(w)), pw.this.u, pw.this.v, pw.this.weight)
        )
    )

    def assign(node, all_edges):
        import collections

        adj: dict = collections.defaultdict(dict)
        total_w = 0.0
        for u, v, w in all_edges or ():
            adj[u][v] = adj[u].get(v, 0.0) + w
            adj[v][u] = adj[v].get(u, 0.0) + w
            total_w += w
        if total_w == 0:
            return node
        comm = {n: n for n in adj}
        deg = {n: sum(adj[n].values()) for n in adj}
        for _ in range(iterations):
            moved = False
            for n in sorted(adj, key=str):
                best, best_gain = comm[n], 0.0
                neigh_comms: dict = collections.defaultdict(float)
                for m, w in adj[n].items():
                    if m != n:
                        neigh_comms[comm[m]] += w
                sigma = collections.defaultdict(float)
                for m in adj:
                    if m != n:
                        sigma[comm[m]] += deg[m]
                for c, w_in in sorted(neigh_comms.items(), key=lambda kv: str(kv[0])):
                    gain = w_in / total_w - deg[n] * sigma[c] / (2 * total_w**2)
                    if gain > best_gain:
                        best, best_gain = c, gain
                if best != comm[n]:
                    comm[n] = best
                    moved = True
            if not moved:
                break
        return comm.get(node, node)

    joined = comm0.join_left(packed_edges, id=pw.left.id).select(
        node=pw.left.node,
        community=pw.apply(assign, pw.left.node, pw.right.all_edges),
    )
    return joined


louvain_communities = louvain_level
