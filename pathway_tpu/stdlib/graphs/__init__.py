"""``pw.graphs`` — graph algorithms over streaming edge tables
(reference ``python/pathway/stdlib/graphs/``: ``graph.py:77,121``,
``bellman_ford/impl.py``, ``pagerank/impl.py``,
``louvain_communities/impl.py``).  All incremental via ``pw.iterate``."""

from __future__ import annotations

import dataclasses
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = [
    "Graph",
    "WeightedGraph",
    "bellman_ford",
    "pagerank",
    "louvain_level",
    "louvain_communities",
    "exact_modularity",
]


@dataclasses.dataclass
class Graph:
    """Edges table with columns u, v (reference ``graphs/graph.py:77``)."""

    edges: Table

    def without_self_loops(self) -> "Graph":
        return Graph(self.edges.filter(pw.this.u != pw.this.v))


@dataclasses.dataclass
class WeightedGraph(Graph):
    """Edges carry a ``weight`` column (reference ``graph.py:121``)."""

    @classmethod
    def from_edges(cls, edges: Table, weight: Any = None) -> "WeightedGraph":
        if weight is not None and getattr(weight, "_name", "weight") != "weight":
            edges = edges.select(u=pw.this.u, v=pw.this.v, weight=weight)
        return cls(edges)


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Single-source shortest paths (reference
    ``graphs/bellman_ford/impl.py``): ``vertices`` has a ``dist`` column
    (0 for sources, None/inf otherwise); ``edges`` has u, v, dist."""
    import math

    INF = math.inf

    start = vertices.select(
        dist=pw.apply(lambda d: INF if d is None else float(d), pw.this.dist)
    )

    def body(state: Table, edges: Table) -> Table:
        # candidate distances: via each incoming edge
        relaxed = edges.join(state, pw.left.u == pw.right.id).select(
            v=pw.left.v,
            cand=pw.apply(
                lambda du, w: du + float(w), pw.right.dist, pw.left.dist
            ),
        )
        best = relaxed.groupby(relaxed.v, id=relaxed.v).reduce(
            cand=pw.reducers.min(relaxed.cand)
        )
        improved = state.join_left(
            best, pw.left.id == pw.right.id, id=pw.left.id
        ).select(
            dist=pw.apply(
                lambda d, c: d if c is None else min(d, c),
                pw.left.dist,
                pw.right.cand,
            ),
        )
        return improved

    # join on vertex ids: state is keyed by vertex key; edges are
    # read-only context inside the fixpoint
    return pw.iterate(body, state=start, edges=edges)


def pagerank(edges: Table, steps: int = 5, damping: float = 0.85) -> Table:
    """PageRank over an edge table u->v (reference
    ``graphs/pagerank/impl.py``; integer arithmetic there, floats here)."""
    vertices = (
        edges.select(w=pw.this.u)
        .concat_reindex(edges.select(w=pw.this.v))
        .groupby(pw.this.w)
        .reduce(w=pw.this.w)
    )
    degrees = edges.groupby(edges.u).reduce(u=edges.u, deg=pw.reducers.count())
    ranks = vertices.select(w=pw.this.w, rank=pw.apply(lambda _w: 1.0, pw.this.w))

    for _ in range(steps):
        contrib = (
            edges.join(ranks, pw.left.u == pw.right.w)
            .select(v=pw.left.v, part=pw.right.rank, u=pw.left.u)
            .join(degrees, pw.left.u == pw.right.u)
            .select(
                v=pw.left.v,
                part=pw.apply(lambda r, d: r / d, pw.left.part, pw.right.deg),
            )
        )
        summed = contrib.groupby(contrib.v).reduce(
            v=contrib.v, total=pw.reducers.sum(contrib.part)
        )
        ranks = vertices.join_left(
            summed, pw.left.w == pw.right.v, id=pw.left.id
        ).select(
            w=pw.left.w,
            rank=pw.apply(
                lambda t, d=damping: (1 - d) + d * (t or 0.0), pw.right.total
            ),
        )
    return ranks


def louvain_level(
    G: WeightedGraph, iterations: int = 10, total_weight: Table | None = None
) -> Table:
    """One level of Louvain community detection (reference
    ``louvain_communities/impl.py`` ``_louvain_level``, redesigned as a
    host greedy pass over the epoch's aggregated edge set): returns a
    table keyed by vertex with a ``community`` column.

    ``total_weight``: optional 1-row (lower, value, upper) approximation
    table; when given, each vertex's objective uses an ``apx_value``
    delivered via :meth:`Table._gradual_broadcast` — the reference's
    churn-damping route for the global edge-weight sum."""
    edges = G.edges
    vertices = (
        edges.select(w=pw.this.u)
        .concat_reindex(edges.select(w=pw.this.v))
        .groupby(pw.this.w, id=pw.this.w)
        .reduce(w=pw.this.w)
    )
    comm0 = vertices.select(node=pw.this.w, community=pw.this.w)
    if total_weight is not None:
        comm0 = comm0._gradual_broadcast(
            total_weight,
            total_weight.lower,
            total_weight.value,
            total_weight.upper,
        )

    # host-side greedy modularity pass over the (small) aggregated edge set
    packed_edges = edges.reduce(
        all_edges=pw.reducers.tuple(
            pw.apply(lambda u, v, w: (u, v, float(w)), pw.this.u, pw.this.v, pw.this.weight)
        )
    )

    def assign(node, all_edges, apx_total=None):
        import collections

        adj: dict = collections.defaultdict(dict)
        total_w = 0.0
        for u, v, w in all_edges or ():
            adj[u][v] = adj[u].get(v, 0.0) + w
            adj[v][u] = adj[v].get(u, 0.0) + w
            total_w += w
        if apx_total is not None:
            # the gradually-broadcast approximation (within the triplet's
            # [lower, upper] of the true sum) replaces the exact total
            total_w = float(apx_total)
        if total_w == 0:
            return node
        comm = {n: n for n in adj}
        deg = {n: sum(adj[n].values()) for n in adj}
        for _ in range(iterations):
            moved = False
            for n in sorted(adj, key=str):
                best, best_gain = comm[n], 0.0
                neigh_comms: dict = collections.defaultdict(float)
                for m, w in adj[n].items():
                    if m != n:
                        neigh_comms[comm[m]] += w
                sigma = collections.defaultdict(float)
                for m in adj:
                    if m != n:
                        sigma[comm[m]] += deg[m]
                for c, w_in in sorted(neigh_comms.items(), key=lambda kv: str(kv[0])):
                    gain = w_in / total_w - deg[n] * sigma[c] / (2 * total_w**2)
                    if gain > best_gain:
                        best, best_gain = c, gain
                if best != comm[n]:
                    comm[n] = best
                    moved = True
            if not moved:
                break
        return comm.get(node, node)

    if total_weight is not None:
        joined = comm0.join_left(packed_edges, id=pw.left.id).select(
            node=pw.left.node,
            community=pw.apply(
                assign, pw.left.node, pw.right.all_edges, pw.left.apx_value
            ),
        )
    else:
        joined = comm0.join_left(packed_edges, id=pw.left.id).select(
            node=pw.left.node,
            community=pw.apply(assign, pw.left.node, pw.right.all_edges),
        )
    return joined


def _approximate_total_weight(edges: Table, epsilon: float = 0.1) -> Table:
    """1-row (lower, value, upper) window around the total edge weight
    (reference ``_approximate_total_weight``,
    ``louvain_communities/impl.py:263-280``): bounds move only when the
    sum crosses a power of (1+epsilon), so the gradual broadcast barely
    churns as edges stream in."""
    import math

    exact = edges.reduce(m=pw.reducers.sum(pw.this.weight))

    def _floor_pow(x):
        x = max(float(x), 1e-12)
        return (1 + epsilon) ** math.floor(math.log(x, 1 + epsilon))

    def _ceil_pow(x):
        x = max(float(x), 1e-12)
        return (1 + epsilon) ** (math.floor(math.log(x, 1 + epsilon)) + 1)

    return exact.select(
        lower=pw.apply(_floor_pow, pw.this.m),
        value=pw.apply(float, pw.this.m),
        upper=pw.apply(_ceil_pow, pw.this.m),
    )


class louvain_communities:
    """Multi-level Louvain (reference
    ``louvain_communities_fixed_iterations``,
    ``louvain_communities/impl.py:283-338``): repeatedly find one level's
    clustering, contract the graph to cluster vertices (summing parallel
    edge weights), and recurse, with the global total weight delivered to
    every level through :meth:`Table._gradual_broadcast`.

    Attributes (same shape as the reference):

    - ``hierarchical_clustering`` — rows (node, c, level): each vertex or
      intermediate cluster points at its parent cluster one level up.
    - ``clustering_levels`` — rows (v, c, level): every original vertex's
      ancestor at EVERY level (level 0 = itself).
    """

    def __init__(self, G: WeightedGraph, levels: int = 2, apx: float = 0.1):
        total_weight = _approximate_total_weight(G.edges, apx)
        edges = G.edges
        base_vertices = (
            edges.select(w=pw.this.u)
            .concat_reindex(edges.select(w=pw.this.v))
            .groupby(pw.this.w, id=pw.this.w)
            .reduce(w=pw.this.w)
        )
        self.levels = levels
        self.hierarchical_clustering = base_vertices.select(
            node=pw.this.w, c=pw.this.w, level=0
        )
        self.clustering_levels = base_vertices.select(
            v=pw.this.w, c=pw.this.w, level=0
        )
        for lvl in range(levels):
            clustering = louvain_level(
                WeightedGraph(edges), total_weight=total_weight
            )
            self.hierarchical_clustering = self.hierarchical_clustering.concat_reindex(
                clustering.select(
                    node=pw.this.node, c=pw.this.community, level=lvl + 1
                )
            )
            prev = self.clustering_levels.filter(pw.this.level == lvl)
            lifted = prev.join(
                clustering, pw.left.c == pw.right.node
            ).select(v=pw.left.v, c=pw.right.community, level=lvl + 1)
            self.clustering_levels = self.clustering_levels.concat_reindex(lifted)
            # contract: map both endpoints to their communities, merge
            # parallel edges (reference contracted_to_weighted_simple_graph)
            mapped = edges.join(
                clustering, pw.left.u == pw.right.node
            ).select(cu=pw.right.community, v=pw.left.v, weight=pw.left.weight)
            mapped = mapped.join(
                clustering, pw.left.v == pw.right.node
            ).select(u=pw.left.cu, v=pw.right.community, weight=pw.left.weight)
            edges = mapped.groupby(pw.this.u, pw.this.v).reduce(
                pw.this.u, pw.this.v, weight=pw.reducers.sum(pw.this.weight)
            )
        self.final_clustering = self.clustering_levels.filter(
            pw.this.level == levels
        )


def exact_modularity(G: WeightedGraph, C: Table, round_digits: int = 16) -> Table:
    """Modularity of clustering ``C`` (rows: v -> c) over ``G`` — test and
    development helper (reference ``exact_modularity``,
    ``louvain_communities/impl.py:340-385``)."""
    packed_edges = G.edges.reduce(
        es=pw.reducers.tuple(
            pw.apply(
                lambda u, v, w: (u, v, float(w)),
                pw.this.u,
                pw.this.v,
                pw.this.weight,
            )
        )
    )
    packed_c = C.reduce(
        cs=pw.reducers.tuple(pw.apply(lambda v, c: (v, c), pw.this.v, pw.this.c))
    )

    def modularity(es, cs):
        comm = dict(cs or ())
        m = sum(w for _u, _v, w in es or ())
        if m == 0:
            return 0.0
        intra = {}
        deg = {}
        for u, v, w in es:
            deg[u] = deg.get(u, 0.0) + w
            deg[v] = deg.get(v, 0.0) + w
            cu = comm.get(u)
            # endpoints missing from C (e.g. clustering from an earlier
            # epoch's vertex set) contribute degree but no intra weight
            if cu is not None and cu == comm.get(v):
                intra[cu] = intra.get(cu, 0.0) + w
        q = 0.0
        communities = set(comm.values())
        for c in communities:
            tot = sum(d for n, d in deg.items() if comm.get(n) == c)
            q += intra.get(c, 0.0) / m - (tot / (2 * m)) ** 2
        return round(q, round_digits)

    return packed_edges.join(packed_c, id=pw.left.id).select(
        modularity=pw.apply(modularity, pw.left.es, pw.right.cs)
    )
