"""``DataIndex`` — the retrieval entry point over engine external indexes.

Capability parity with reference ``stdlib/indexing/data_index.py:206-473``
(``DataIndex`` with ``query`` / ``query_as_of_now``) and
``nearest_neighbors.py`` / ``bm25.py`` / ``hybrid_index.py`` factories.
TPU re-design: the KNN inner index is the device-resident sharded slab
(:class:`pathway_tpu.parallel.ShardedKnnIndex`); every epoch's queries
are answered with one jitted matmul + top-k.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from pathway_tpu.engine.external_index import ExternalIndexNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference, _wrap
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.engine import graph as eg
from pathway_tpu.stdlib.indexing.adapters import BM25Adapter, HybridAdapter, KnnAdapter

__all__ = [
    "InnerIndex",
    "BruteForceKnn",
    "UsearchKnn",
    "LshKnn",
    "TantivyBM25",
    "HybridIndex",
    "InnerIndexFactory",
    "BruteForceKnnFactory",
    "UsearchKnnFactory",
    "LshKnnFactory",
    "TantivyBM25Factory",
    "HybridIndexFactory",
    "BruteForceKnnMetricKind",
    "DataIndex",
]


class BruteForceKnnMetricKind:
    COS = "cos"
    L2SQ = "l2sq"
    DOT = "dot"


# ---------------------------------------------------------------------------
# Inner indexes


class InnerIndex:
    """Binds index-side columns; subclasses build the host adapter."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
    ):
        self.data_column = data_column
        self.metadata_column = metadata_column
        self.data_table: Table = data_column._table
        self.embedder: Any = None  # optional UDF str -> vector

    def make_adapter(self) -> Any:
        raise NotImplementedError

    def query_payload_expr(self, query_column: ColumnExpression) -> ColumnExpression:
        return query_column


class BruteForceKnn(InnerIndex):
    """Exact KNN on the TPU sharded slab (reference
    ``nearest_neighbors.py:65`` over the Rust brute-force engine index)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        metric: str = BruteForceKnnMetricKind.COS,
        mesh: Any = None,
        dtype: Any = None,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
    ):
        super().__init__(data_column, metadata_column)
        self.dimensions = dimensions
        self.reserved_space = reserved_space
        self.metric = metric
        self.mesh = mesh
        self.dtype = dtype
        # live-maintenance knobs for the segment layer fronting the
        # index (delta segment + background merge; PATHWAY_INDEX_* env
        # defaults apply when unset)
        self.delta_cap = delta_cap
        self.tombstone_fraction = tombstone_fraction
        self.auto_merge = auto_merge

    def _maintenance_kwargs(self) -> dict:
        return {
            "delta_cap": self.delta_cap,
            "tombstone_fraction": self.tombstone_fraction,
            "auto_merge": self.auto_merge,
        }

    def make_adapter(self) -> Any:
        return KnnAdapter(
            self.dimensions,
            metric=self.metric,
            capacity=self.reserved_space,
            mesh=self.mesh,
            dtype=self.dtype,
            **self._maintenance_kwargs(),
        )


class UsearchKnn(BruteForceKnn):
    """Approximate KNN (reference ``USearchKnn`` fronting an HNSW,
    ``src/external_integration/usearch_integration.rs``).  Backed by the
    native host HNSW graph (``native/pathway_native.cpp`` ``hnsw_*`` via
    :class:`~pathway_tpu.stdlib.indexing.hnsw.HnswIndex`) — the graph
    walk is pointer-chasing, so like the reference it runs on the host,
    not on the TPU.  Pass ``nlist``/``nprobe`` to choose the TPU-resident
    IVF-flat alternative instead (:class:`pathway_tpu.parallel.IvfKnnIndex`:
    k-means cells in HBM, centroid matmul -> gather -> einsum + top-k),
    which trades a little recall for device-side batch throughput."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        metric: str = BruteForceKnnMetricKind.COS,
        mesh: Any = None,
        dtype: Any = None,
        nlist: int | None = None,
        nprobe: int | None = None,
        M: int = 16,
        ef_construction: int = 128,
        ef_search: int = 64,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
    ):
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            mesh=mesh,
            dtype=dtype,
            delta_cap=delta_cap,
            tombstone_fraction=tombstone_fraction,
            auto_merge=auto_merge,
        )
        self.nlist = nlist
        self.nprobe = nprobe
        self.M = M
        self.ef_construction = ef_construction
        self.ef_search = ef_search

    def make_adapter(self) -> Any:
        if self.mesh is not None:
            # HNSW/IVF are single-host; a mesh caller sized reserved_space
            # for the aggregate HBM of all chips — give them the SHARDED
            # exact index rather than silently dropping the mesh
            import logging

            logging.getLogger("pathway_tpu").info(
                "UsearchKnn: mesh given -> using the mesh-sharded exact "
                "brute-force index (graph/IVF ANN is single-host)"
            )
            return super().make_adapter()
        if self.nlist is not None or self.nprobe is not None:
            if self.metric == BruteForceKnnMetricKind.L2SQ:
                return super().make_adapter()  # IVF cells are ip-trained
            from pathway_tpu.stdlib.indexing.adapters import IvfAdapter

            return IvfAdapter(
                self.dimensions,
                metric=self.metric,
                capacity=self.reserved_space,
                dtype=self.dtype,
                nlist=self.nlist,
                nprobe=self.nprobe,
                **self._maintenance_kwargs(),
            )
        from pathway_tpu.stdlib.indexing.adapters import HnswAdapter

        return HnswAdapter(
            self.dimensions,
            metric=self.metric,
            M=self.M,
            ef_construction=self.ef_construction,
            ef_search=self.ef_search,
            **self._maintenance_kwargs(),
        )


class LshKnn(BruteForceKnn):
    """LSH-bucketed KNN API surface (reference ``LshKnn``,
    ``nearest_neighbors.py:414``).  Exact TPU matmul under the hood (see
    :class:`UsearchKnn` note); ``stdlib.ml`` keeps a true LSH classifier."""


class TantivyBM25(InnerIndex):
    """Full-text BM25 (reference ``bm25.py:41-135``; host inverted index
    standing in for tantivy)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
    ):
        super().__init__(data_column, metadata_column)

    def make_adapter(self) -> Any:
        return BM25Adapter()


class HybridIndex(InnerIndex):
    """Reciprocal-rank fusion of several inner indexes (reference
    ``hybrid_index.py:14-147``)."""

    def __init__(self, inner_indexes: Sequence[InnerIndex], *, k: float = 60.0):
        assert inner_indexes, "HybridIndex needs at least one inner index"
        first = inner_indexes[0]
        super().__init__(first.data_column, first.metadata_column)
        self.children = list(inner_indexes)
        self.rrf_k = k

    def make_adapter(self) -> Any:
        return HybridAdapter([c.make_adapter() for c in self.children], self.rrf_k)


# ---------------------------------------------------------------------------
# Factories (reference ``InnerIndexFactory`` family)


class InnerIndexFactory:
    def build_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnReference | None = None,
    ) -> InnerIndex:
        raise NotImplementedError

    def build_data_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnReference | None = None,
    ) -> "DataIndex":
        return DataIndex(
            data_table, self.build_index(data_column, data_table, metadata_column)
        )


@dataclasses.dataclass
class BruteForceKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    metric: str = BruteForceKnnMetricKind.COS
    embedder: Any = None
    mesh: Any = None
    delta_cap: int | None = None
    tombstone_fraction: float | None = None
    auto_merge: bool | None = None

    _cls = BruteForceKnn

    def build_index(self, data_column, data_table, metadata_column=None) -> InnerIndex:
        dims = self.dimensions
        if dims is None:
            if self.embedder is None:
                raise ValueError("dimensions required when no embedder is given")
            dims = _embedder_dimension(self.embedder)
        idx = self._cls(
            data_column,
            metadata_column,
            dimensions=dims,
            reserved_space=self.reserved_space,
            metric=self.metric,
            mesh=self.mesh,
            delta_cap=self.delta_cap,
            tombstone_fraction=self.tombstone_fraction,
            auto_merge=self.auto_merge,
        )
        idx.embedder = self.embedder
        return idx


class UsearchKnnFactory(BruteForceKnnFactory):
    _cls = UsearchKnn


class LshKnnFactory(BruteForceKnnFactory):
    _cls = LshKnn


@dataclasses.dataclass
class TantivyBM25Factory(InnerIndexFactory):
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_index(self, data_column, data_table, metadata_column=None) -> InnerIndex:
        return TantivyBM25(data_column, metadata_column)


@dataclasses.dataclass
class HybridIndexFactory(InnerIndexFactory):
    retriever_factories: list[InnerIndexFactory] = dataclasses.field(default_factory=list)
    k: float = 60.0

    def build_index(self, data_column, data_table, metadata_column=None) -> InnerIndex:
        children = [
            f.build_index(data_column, data_table, metadata_column)
            for f in self.retriever_factories
        ]
        idx = HybridIndex(children, k=self.k)
        return idx


def _embedder_dimension(embedder: Any) -> int:
    """Probe an embedder UDF for its output width."""
    import inspect

    import numpy as np

    if hasattr(embedder, "get_embedding_dimension"):
        return int(embedder.get_embedding_dimension())
    batch = getattr(embedder, "__batch__", None)
    if batch is not None:
        # epoch-batch contract: one LIST in, aligned list out
        probe = batch(["probe"])[0]
    elif hasattr(embedder, "__wrapped__"):
        probe = embedder.__wrapped__("probe")
    else:
        probe = embedder("probe")
    if inspect.isawaitable(probe):
        import asyncio

        probe = asyncio.run(probe)
    return int(np.asarray(probe).reshape(-1).shape[0])


# ---------------------------------------------------------------------------
# DataIndex

REPLY_ID = "_pw_index_reply_id"
REPLY_SCORE = "_pw_index_reply_score"
REPLY_DATA = "_pw_index_reply"


class DataIndex:
    """Queryable live index over ``data_table``.

    ``query_as_of_now`` answers each query once, against the index state
    at its arrival epoch (reference as-of-now semantics);
    ``query`` keeps answers consistent as the corpus changes.
    """

    def __init__(self, data_table: Table, inner_index: InnerIndex):
        self.data_table = data_table
        self.inner = inner_index

    # -- index side -----------------------------------------------------
    def _index_side(self) -> tuple[eg.Node, Callable, Callable, Callable]:
        orig_table = self.inner.data_table
        table = orig_table
        data_expr = self.inner.data_column
        if self.inner.embedder is not None and _is_str(table, data_expr):
            table = table.with_columns(_pw_index_payload=self.inner.embedder(data_expr))
            payload_ref: ColumnExpression = table["_pw_index_payload"]
        else:
            payload_ref = data_expr
        # HybridIndex: payload is a tuple with one element per child index,
        # each passed through that child's own embedder when it has one
        if isinstance(self.inner, HybridIndex):
            for ci, child in enumerate(self.inner.children):
                if child.embedder is not None and _is_str(orig_table, child.data_column):
                    expr = _retable(child.data_column, orig_table, table)
                    table = table.with_columns(
                        **{f"_pw_index_payload_{ci}": child.embedder(expr)}
                    )
            layout = table._layout()
            child_fns = []
            for ci, child in enumerate(self.inner.children):
                if f"_pw_index_payload_{ci}" in table._column_names:
                    expr: Any = table[f"_pw_index_payload_{ci}"]
                else:
                    expr = _retable(child.data_column, orig_table, table)
                child_fns.append(_wrap(expr)._compile(layout.resolver))
            payload_fn = lambda key, values, fns=child_fns: tuple(  # noqa: E731
                f((key, values)) for f in fns
            )
        else:
            layout = table._layout()
            c = _wrap(payload_ref)._compile(layout.resolver)
            payload_fn = lambda key, values: c((key, values))  # noqa: E731
        cols = orig_table._column_names
        n = len(cols)

        def data_fn(key, values):
            return dict(zip(cols, values[:n]))

        if self.inner.metadata_column is not None:
            meta_expr = _retable(self.inner.metadata_column, orig_table, table)
            mc = _wrap(meta_expr)._compile(layout.resolver)

            def meta_fn(key, values):
                m = mc((key, values))
                if hasattr(m, "as_dict"):
                    m = m.as_dict()
                return m if isinstance(m, dict) else None

        else:
            meta_fn = lambda key, values: None  # noqa: E731
        return table._node, payload_fn, data_fn, meta_fn

    # -- query side -----------------------------------------------------
    def _build(
        self,
        query_column: ColumnExpression,
        number_of_matches: Any,
        metadata_filter: Any,
        as_of_now: bool,
    ) -> Table:
        qref = query_column
        query_table: Table = (
            qref._table if isinstance(qref, ColumnReference) else None
        )
        if query_table is None:
            refs = _wrap(qref)._references()
            tables = {r._table for r in refs}
            assert len(tables) == 1, "query expression must reference one table"
            query_table = tables.pop()
        orig_query_table = query_table
        if self.inner.embedder is not None and _is_str(query_table, qref):
            query_table = query_table.with_columns(
                _pw_query_payload=self.inner.embedder(qref)
            )
            # with_columns preserves parent column names/positions, so
            # re-anchor sibling expressions (k, filter) onto the new table
            number_of_matches = _retable(
                number_of_matches, orig_query_table, query_table
            )
            metadata_filter = _retable(metadata_filter, orig_query_table, query_table)
            payload_expr: ColumnExpression = query_table["_pw_query_payload"]
        else:
            payload_expr = qref
        if isinstance(self.inner, HybridIndex):
            # per-child query payloads, each through that child's embedder
            base_expr = payload_expr
            child_exprs: list[Any] = []
            hybrid_base = query_table
            for ci, child in enumerate(self.inner.children):
                if child.embedder is not None and _is_str(orig_query_table, qref):
                    e = _retable(base_expr, orig_query_table, query_table)
                    query_table = query_table.with_columns(
                        **{f"_pw_query_payload_{ci}": child.embedder(e)}
                    )
                    child_exprs.append(f"_pw_query_payload_{ci}")
                else:
                    child_exprs.append(None)
            if query_table is not hybrid_base:
                number_of_matches = _retable(
                    number_of_matches, orig_query_table, query_table
                )
                metadata_filter = _retable(
                    metadata_filter, orig_query_table, query_table
                )
            layout = query_table._layout()
            child_fns = []
            for ci, name in enumerate(child_exprs):
                if name is not None:
                    expr: Any = query_table[name]
                else:
                    expr = _retable(base_expr, orig_query_table, query_table)
                child_fns.append(_wrap(expr)._compile(layout.resolver))
            q_payload_fn = lambda key, values, fns=child_fns: tuple(  # noqa: E731
                f((key, values)) for f in fns
            )
        else:
            layout = query_table._layout()
            pc = _wrap(payload_expr)._compile(layout.resolver)
            q_payload_fn = lambda key, values: pc((key, values))  # noqa: E731

        if isinstance(number_of_matches, ColumnExpression):
            kc = _wrap(query_table._subst(number_of_matches))._compile(layout.resolver)
            k_fn = lambda key, values: kc((key, values))  # noqa: E731
        else:
            k_const = int(number_of_matches)
            k_fn = lambda key, values: k_const  # noqa: E731

        if metadata_filter is None:
            f_fn = None
        elif isinstance(metadata_filter, ColumnExpression):
            fc = _wrap(query_table._subst(metadata_filter))._compile(layout.resolver)
            f_fn = lambda key, values: fc((key, values))  # noqa: E731
        else:
            f_fn = lambda key, values: metadata_filter  # noqa: E731

        index_node, payload_fn, data_fn, meta_fn = self._index_side()
        node = ExternalIndexNode(
            G.engine_graph,
            index_node,
            query_table._node,
            self.inner.make_adapter(),
            index_payload_fn=payload_fn,
            index_data_fn=data_fn,
            index_meta_fn=meta_fn,
            query_payload_fn=q_payload_fn,
            query_k_fn=k_fn,
            query_filter_fn=f_fn,
            as_of_now=as_of_now,
        )
        # the index side is a keyed upsert stream into adapter state:
        # applying same-key updates out of order serves stale vectors
        # (distribution pass treats input 0 as order-sensitive, PW-X001)
        node.meta["index"] = {
            "upsert": True,
            "order_sensitive": True,
            "adapter": type(self.inner).__name__,
        }
        cols = query_table._column_names + [REPLY_ID, REPLY_SCORE, REPLY_DATA]
        dtypes = dict(query_table._dtypes)
        dtypes[REPLY_ID] = dt.ANY
        dtypes[REPLY_SCORE] = dt.ANY
        dtypes[REPLY_DATA] = dt.ANY
        result = Table(
            node,
            cols,
            dtypes,
            name="index_reply",
            layout_token=query_table._layout_token,
        )
        if any(c.startswith("_pw_query_payload") for c in result._column_names):
            keep = [c for c in cols if not c.startswith("_pw_query_payload")]
            result = result.select(**{c: result[c] for c in keep})
        return result

    def query_as_of_now(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: Any = None,
    ) -> Table:
        out = self._build(query_column, number_of_matches, metadata_filter, True)
        return out if collapse_rows else _flatten_replies(out)

    def query(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: Any = None,
    ) -> Table:
        out = self._build(query_column, number_of_matches, metadata_filter, False)
        return out if collapse_rows else _flatten_replies(out)


def _retable(expr: Any, old: Table, new: Table) -> Any:
    """Rebuild ``expr`` with references to ``old`` re-anchored on ``new``
    (valid when ``new`` preserves ``old``'s column names, e.g. the result
    of ``with_columns``)."""
    if not isinstance(expr, ColumnExpression):
        return expr
    if isinstance(expr, ColumnReference):
        if expr._table is old and expr._name in new._column_names:
            return ColumnReference(new, expr._name)
        return expr
    children = list(expr._children())
    if not children:
        return expr
    return expr._rebuild([_retable(c, old, new) for c in children])


def _is_str(table: Table, expr: ColumnExpression) -> bool:
    if isinstance(expr, ColumnReference) and expr._name in table._dtypes:
        d = table._dtypes[expr._name].strip_optional()
        return d in (dt.STR, dt.ANY)
    return True  # unknown expression: assume text when an embedder exists


def _flatten_replies(result: Table) -> Table:
    """One row per match: reply tuples zipped + flattened + unpacked."""
    zipped = result.select(
        *[result[c] for c in result._column_names if not c.startswith("_pw_index_reply")],
        _pw_reply_zip=_zip3(result[REPLY_ID], result[REPLY_SCORE], result[REPLY_DATA]),
    )
    flat = zipped.flatten(zipped["_pw_reply_zip"])
    base = [c for c in flat._column_names if c != "_pw_reply_zip"]
    from pathway_tpu.internals.expression import apply as pw_apply

    return flat.select(
        *[flat[c] for c in base],
        **{
            REPLY_ID: pw_apply(lambda z: z[0], flat["_pw_reply_zip"]),
            REPLY_SCORE: pw_apply(lambda z: z[1], flat["_pw_reply_zip"]),
            REPLY_DATA: pw_apply(lambda z: z[2], flat["_pw_reply_zip"]),
        },
    )


def _zip3(a: Any, b: Any, c: Any) -> ColumnExpression:
    from pathway_tpu.internals.expression import apply as pw_apply

    return pw_apply(
        lambda x, y, z: tuple(zip(x or (), y or (), z or ())), a, b, c
    )
