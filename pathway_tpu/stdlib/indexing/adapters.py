"""Host adapters bridging engine index operators to concrete indexes.

Equivalent of the reference's ``ExternalIndex`` implementations
(``src/external_integration/*.rs``): the KNN adapter fronts the
TPU-resident :class:`~pathway_tpu.parallel.ShardedKnnIndex`; BM25 is a
host inverted index (the tantivy equivalent).  Metadata filtering
(JMESPath-subset, see :mod:`.filters`) is applied host-side with
over-fetch, mirroring the reference's filter-then-trim flow
(``src/external_integration/mod.rs:92-181``).
"""

from __future__ import annotations

import heapq
import math
from collections import defaultdict
from typing import Any, Callable, Sequence

import numpy as np

__all__ = [
    "KnnAdapter",
    "IvfAdapter",
    "HnswAdapter",
    "BM25Adapter",
    "HybridAdapter",
]

_OVERFETCH = 4


def _segmented(main, delta_cap, tombstone_fraction, auto_merge):
    from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

    return SegmentedIndex(
        main,
        delta_cap=delta_cap,
        tombstone_fraction=tombstone_fraction,
        auto_merge=auto_merge,
    )


class KnnAdapter:
    """(key, vector) index over :class:`ShardedKnnIndex` + host metadata.

    The concrete index is fronted by a
    :class:`~pathway_tpu.stdlib.indexing.segments.SegmentedIndex`: live
    upserts/deletes land in a delta segment + tombstone set and a
    background merge compacts them into the sealed main segment
    (``delta_cap``/``tombstone_fraction``/``auto_merge`` knobs, env
    defaults ``PATHWAY_INDEX_*``)."""

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        capacity: int = 1024,
        mesh: Any = None,
        dtype: Any = None,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
    ):
        import jax.numpy as jnp

        from pathway_tpu.parallel import ShardedKnnIndex

        self.index = _segmented(
            ShardedKnnIndex(
                dim,
                metric=metric,
                capacity=capacity,
                mesh=mesh,
                dtype=dtype or jnp.float32,
            ),
            delta_cap,
            tombstone_fraction,
            auto_merge,
        )
        self.meta: dict[Any, dict | None] = {}

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        prepared = []
        for key, payload in items:
            if isinstance(payload, tuple) and len(payload) == 2 and isinstance(payload[1], dict):
                vec, meta = payload
            else:
                vec, meta = payload, None
            self.meta[key] = meta
            prepared.append((key, np.asarray(vec, np.float32)))
        self.index.add(prepared)

    def remove(self, keys: Sequence[Any]) -> None:
        for k in keys:
            self.meta.pop(k, None)
        self.index.remove(keys)

    def set_meta(self, key: Any, meta: dict | None) -> None:
        self.meta[key] = meta

    def search(
        self,
        payloads: Sequence[Any],
        k: Sequence[int],
        filters: Sequence[Callable[[dict], bool] | None],
    ) -> list[list[tuple[Any, float]]]:
        if not payloads:
            return []
        kmax = max(list(k) + [0])
        if kmax == 0:
            return [[] for _ in payloads]
        fetch = kmax * (_OVERFETCH if any(f is not None for f in filters) else 1)
        fetch = min(max(fetch, kmax), max(len(self.index), 1))
        q = np.stack([np.asarray(p, np.float32).reshape(-1) for p in payloads])
        raw = self.index.search(q, fetch)
        out = []
        for qi, reply in enumerate(raw):
            f = filters[qi]
            if f is not None:
                reply = [(key, s) for key, s in reply if f(self.meta.get(key) or {})]
            out.append(reply[: k[qi]])
        return out

    # ------------------------------------------------- persistence / stats

    def state_dict(self) -> dict:
        return {"index": self.index.state_dict(), "meta": dict(self.meta)}

    def load_state_dict(self, state: dict) -> None:
        self.index.load_state_dict(state["index"])
        self.meta = dict(state["meta"])

    def stats(self) -> dict:
        s = getattr(self.index, "stats", None)
        return s() if s is not None else {"size": len(self.index)}


class HnswAdapter(KnnAdapter):
    """(key, vector) index over the host HNSW graph
    (:class:`~pathway_tpu.stdlib.indexing.hnsw.HnswIndex`), the
    reference's usearch role (``usearch_integration.rs``).  Same contract
    and metadata-filter flow as :class:`KnnAdapter`."""

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        M: int = 16,
        ef_construction: int = 128,
        ef_search: int = 64,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
        **_ignored: Any,
    ):
        from pathway_tpu.stdlib.indexing.hnsw import HnswIndex

        self.index = _segmented(
            HnswIndex(
                dim,
                metric=metric,
                M=M,
                ef_construction=ef_construction,
                ef_search=ef_search,
            ),
            delta_cap,
            tombstone_fraction,
            auto_merge,
        )
        self.meta: dict[Any, dict | None] = {}


class BM25Adapter:
    """Incremental BM25 full-text index (tantivy-equivalent,
    ``src/external_integration/tantivy_integration.rs``)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75, tokenizer: Callable[[str], list[str]] | None = None):
        self.k1 = k1
        self.b = b
        self._tokenize = tokenizer or (lambda s: [t for t in _simple_tokens(s)])
        self.postings: dict[str, dict[Any, int]] = defaultdict(dict)
        self.doc_len: dict[Any, int] = {}
        self.doc_terms: dict[Any, list[str]] = {}
        self.meta: dict[Any, dict | None] = {}
        self.total_len = 0

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        for key, payload in items:
            if isinstance(payload, tuple) and len(payload) == 2 and isinstance(payload[1], dict):
                text, meta = payload
            else:
                text, meta = payload, None
            if key in self.doc_len:
                self._remove_one(key)
            toks = self._tokenize(str(text))
            self.doc_terms[key] = toks
            self.doc_len[key] = len(toks)
            self.total_len += len(toks)
            self.meta[key] = meta
            for t in toks:
                self.postings[t][key] = self.postings[t].get(key, 0) + 1

    def _remove_one(self, key: Any) -> None:
        toks = self.doc_terms.pop(key, [])
        self.total_len -= self.doc_len.pop(key, 0)
        self.meta.pop(key, None)
        for t in set(toks):
            d = self.postings.get(t)
            if d is not None:
                d.pop(key, None)
                if not d:
                    del self.postings[t]

    def remove(self, keys: Sequence[Any]) -> None:
        for k in keys:
            self._remove_one(k)

    def set_meta(self, key: Any, meta: dict | None) -> None:
        self.meta[key] = meta

    def __len__(self) -> int:
        return len(self.doc_len)

    def state_dict(self) -> dict:
        return {
            "postings": {t: dict(d) for t, d in self.postings.items()},
            "doc_len": dict(self.doc_len),
            "doc_terms": dict(self.doc_terms),
            "meta": dict(self.meta),
            "total_len": self.total_len,
        }

    def load_state_dict(self, state: dict) -> None:
        self.postings = defaultdict(dict, {t: dict(d) for t, d in state["postings"].items()})
        self.doc_len = dict(state["doc_len"])
        self.doc_terms = dict(state["doc_terms"])
        self.meta = dict(state["meta"])
        self.total_len = state["total_len"]

    def stats(self) -> dict:
        return {"size": len(self.doc_len), "terms": len(self.postings)}

    def search(
        self,
        payloads: Sequence[Any],
        k: Sequence[int],
        filters: Sequence[Callable[[dict], bool] | None],
    ) -> list[list[tuple[Any, float]]]:
        n = len(self.doc_len)
        avgdl = (self.total_len / n) if n else 1.0
        out = []
        for qi, payload in enumerate(payloads):
            scores: dict[Any, float] = defaultdict(float)
            for term in self._tokenize(str(payload)):
                plist = self.postings.get(term)
                if not plist:
                    continue
                idf = math.log(1.0 + (n - len(plist) + 0.5) / (len(plist) + 0.5))
                for key, tf in plist.items():
                    dl = self.doc_len[key]
                    denom = tf + self.k1 * (1 - self.b + self.b * dl / avgdl)
                    scores[key] += idf * tf * (self.k1 + 1) / denom
            f = filters[qi]
            items: Any = scores.items()
            if f is not None:
                # filter BEFORE top-k selection so a restrictive filter
                # still yields k matching docs when they exist
                items = [
                    (key, s) for key, s in items if f(self.meta.get(key) or {})
                ]
            # heap selection instead of a full sort of every matching doc:
            # O(N log k); same ordering as sorted(..)[:k] incl. tie-break
            ranked = heapq.nsmallest(
                k[qi], items, key=lambda kv: (-kv[1], str(kv[0]))
            )
            out.append([(key, float(s)) for key, s in ranked])
        return out


class HybridAdapter:
    """Reciprocal-rank fusion over child adapters (reference
    ``HybridIndex``, ``stdlib/indexing/hybrid_index.py:14-147``).
    Payloads are tuples with one element per child."""

    def __init__(self, children: Sequence[Any], rrf_k: float = 60.0):
        self.children = list(children)
        self.rrf_k = rrf_k

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        for ci, child in enumerate(self.children):
            child.add([(key, payload[ci]) for key, payload in items])

    def remove(self, keys: Sequence[Any]) -> None:
        for child in self.children:
            child.remove(keys)

    def set_meta(self, key: Any, meta: dict | None) -> None:
        for child in self.children:
            if hasattr(child, "set_meta"):
                child.set_meta(key, meta)

    def state_dict(self) -> dict:
        return {
            "children": [
                child.state_dict() if hasattr(child, "state_dict") else None
                for child in self.children
            ]
        }

    def load_state_dict(self, state: dict) -> None:
        for child, sub in zip(self.children, state["children"]):
            if sub is not None and hasattr(child, "load_state_dict"):
                child.load_state_dict(sub)

    def stats(self) -> dict:
        return {
            f"child{ci}": child.stats()
            for ci, child in enumerate(self.children)
            if hasattr(child, "stats")
        }

    def search(self, payloads, k, filters):
        per_child = []
        for ci, child in enumerate(self.children):
            child_payloads = [p[ci] for p in payloads]
            fetch = [kk * 2 for kk in k]
            per_child.append(child.search(child_payloads, fetch, filters))
        out = []
        for qi in range(len(payloads)):
            fused: dict[Any, float] = defaultdict(float)
            for replies in per_child:
                for rank, (key, _s) in enumerate(replies[qi]):
                    fused[key] += 1.0 / (self.rrf_k + rank + 1)
            ranked = sorted(fused.items(), key=lambda kv: (-kv[1], str(kv[0])))
            out.append([(key, float(s)) for key, s in ranked[: k[qi]]])
        return out


def _simple_tokens(s: str):
    import re

    return re.findall(r"[a-z0-9]+", s.lower())


class IvfAdapter(KnnAdapter):
    """(key, vector) index over the approximate :class:`IvfKnnIndex`
    (reference USearch HNSW role; see
    ``pathway_tpu/parallel/ivf_knn.py``)."""

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        capacity: int = 1024,
        dtype: Any = None,
        nlist: int | None = None,
        nprobe: int | None = None,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
    ):
        import jax.numpy as jnp

        from pathway_tpu.parallel import IvfKnnIndex

        self.index = _segmented(
            IvfKnnIndex(
                dim,
                metric=metric,
                capacity=capacity,
                dtype=dtype or jnp.bfloat16,
                nlist=nlist,
                nprobe=nprobe,
            ),
            delta_cap,
            tombstone_fraction,
            auto_merge,
        )
        self.meta: dict[Any, dict | None] = {}
