"""``pw.indexing`` — live retrieval indexes over streaming tables.

Capability parity with reference ``python/pathway/stdlib/indexing/``:
``DataIndex`` (``data_index.py:206-473``), brute-force / usearch / LSH
KNN (``nearest_neighbors.py:65-547``), ``TantivyBM25`` (``bm25.py``),
``HybridIndex`` RRF fusion (``hybrid_index.py``), sorting index
(``sorting.py``).  The KNN path is TPU-native: a sharded HBM slab
searched by jitted matmul + top-k (see
:mod:`pathway_tpu.parallel.sharded_knn`).
"""

from pathway_tpu.stdlib.indexing.adapters import BM25Adapter, HybridAdapter, KnnAdapter
from pathway_tpu.stdlib.indexing.data_index import (
    BruteForceKnn,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    DataIndex,
    HybridIndex,
    HybridIndexFactory,
    InnerIndex,
    InnerIndexFactory,
    LshKnn,
    LshKnnFactory,
    TantivyBM25,
    TantivyBM25Factory,
    UsearchKnn,
    UsearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.filters import compile_filter
from pathway_tpu.stdlib.indexing.segments import SegmentedIndex
from pathway_tpu.stdlib.indexing.sorting import retrieve_prev_next_values
from pathway_tpu.stdlib.indexing.vector_document_index import (
    VectorDocumentIndex,
    default_brute_force_knn_document_index,
    default_full_text_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)

__all__ = [
    "DataIndex",
    "InnerIndex",
    "InnerIndexFactory",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "UsearchKnn",
    "UsearchKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexFactory",
    "KnnAdapter",
    "BM25Adapter",
    "HybridAdapter",
    "SegmentedIndex",
    "compile_filter",
    "retrieve_prev_next_values",
    "VectorDocumentIndex",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_usearch_knn_document_index",
    "default_full_text_document_index",
]
