"""Metadata filter expressions for index queries.

The reference filters index matches with JMESPath expressions plus a
custom ``globmatch`` function (``src/external_integration/mod.rs:92-181``).
jmespath isn't available in this environment, so this is a small
evaluator for the subset those filters actually use:

- comparisons: ``==  !=  <  <=  >  >=`` (backtick, single- or
  double-quoted literals; bare numbers);
- boolean: ``&&  ||  !``, parentheses;
- dotted field paths into the metadata dict (``owner.name``);
- functions: ``contains(haystack, needle)``,
  ``globmatch('pattern', field)``.

``compile_filter(expr)`` returns ``metadata_dict -> bool``.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable

__all__ = ["compile_filter"]

_TOKEN = re.compile(
    r"\s*(?:(?P<op>==|!=|<=|>=|&&|\|\||[!<>()=,])"
    r"|(?P<backtick>`[^`]*`)"
    r"|(?P<string>'[^']*'|\"[^\"]*\")"
    r"|(?P<number>-?\d+(?:\.\d+)?)"
    r"|(?P<name>[A-Za-z_][\w.]*))"
)


def _tokenize(src: str) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise ValueError(f"bad filter syntax at: {src[pos:]!r}")
        pos = m.end()
        for kind in ("op", "backtick", "string", "number", "name"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def eat(self, kind: str | None = None, value: str | None = None) -> tuple[str, str]:
        k, v = self.toks[self.i]
        if (kind and k != kind) or (value and v != value):
            raise ValueError(f"unexpected token {v!r} (wanted {value or kind})")
        self.i += 1
        return k, v

    # expr := or_expr
    def parse(self) -> Callable[[dict], Any]:
        e = self._or()
        self.eat("end")
        return e

    def _or(self):
        left = self._and()
        while self.peek() == ("op", "||"):
            self.eat()
            right = self._and()
            left = (lambda l, r: lambda m: bool(l(m)) or bool(r(m)))(left, right)
        return left

    def _and(self):
        left = self._not()
        while self.peek() == ("op", "&&"):
            self.eat()
            right = self._not()
            left = (lambda l, r: lambda m: bool(l(m)) and bool(r(m)))(left, right)
        return left

    def _not(self):
        if self.peek() == ("op", "!"):
            self.eat()
            inner = self._not()
            return lambda m: not bool(inner(m))
        return self._cmp()

    _CMPS: dict[str, Callable[[Any, Any], bool]] = {
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
        "<": lambda a, b: a is not None and b is not None and a < b,
        "<=": lambda a, b: a is not None and b is not None and a <= b,
        ">": lambda a, b: a is not None and b is not None and a > b,
        ">=": lambda a, b: a is not None and b is not None and a >= b,
    }

    def _cmp(self):
        left = self._atom()
        k, v = self.peek()
        if k == "op" and v in self._CMPS:
            self.eat()
            right = self._atom()
            op = self._CMPS[v]
            return (lambda l, r, op: lambda m: op(l(m), r(m)))(left, right, op)
        return left

    def _atom(self):
        k, v = self.peek()
        if k == "op" and v == "(":
            self.eat()
            e = self._or()
            self.eat("op", ")")
            return e
        if k == "backtick":
            self.eat()
            lit = _parse_literal(v[1:-1])
            return lambda m: lit
        if k == "string":
            self.eat()
            s = v[1:-1]
            return lambda m: s
        if k == "number":
            self.eat()
            n = float(v) if "." in v else int(v)
            return lambda m: n
        if k == "name":
            self.eat()
            if self.peek() == ("op", "("):
                return self._call(v)
            path = v.split(".")

            def lookup(m: dict, path=path):
                cur: Any = m
                for p in path:
                    if not isinstance(cur, dict):
                        return None
                    cur = cur.get(p)
                return cur

            return lookup
        raise ValueError(f"unexpected token {v!r}")

    def _call(self, fname: str):
        self.eat("op", "(")
        args = [self._or()]
        while self.peek() == ("op", ","):
            self.eat()
            args.append(self._or())
        self.eat("op", ")")
        if fname == "contains":
            a, b = args
            return lambda m: (lambda h, n: n in h if h is not None else False)(a(m), b(m))
        if fname == "globmatch":
            pat, field = args
            return lambda m: (
                lambda p, f: fnmatch.fnmatch(str(f), str(p))
                if f is not None and p is not None
                else False
            )(pat(m), field(m))
        raise ValueError(f"unknown filter function {fname!r}")


def _parse_literal(raw: str) -> Any:
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    if raw == "null":
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if len(raw) >= 2 and raw[0] in "'\"" and raw[-1] == raw[0]:
        return raw[1:-1]
    return raw


_COMPILE_CACHE: dict[str, Callable[[dict], bool]] = {}
_COMPILE_CACHE_MAX = 1024


def compile_filter(expr: str) -> Callable[[dict], bool]:
    """Compile a filter expression into ``metadata -> bool``; metadata is
    the per-document dict captured by the index.  Compilations are memoized
    (filters are usually a handful of constant strings re-used per query)."""
    cached = _COMPILE_CACHE.get(expr)
    if cached is not None:
        return cached
    fn = _Parser(_tokenize(expr)).parse()

    def run(meta: dict | None) -> bool:
        try:
            return bool(fn(meta or {}))
        except Exception:
            return False

    if len(_COMPILE_CACHE) < _COMPILE_CACHE_MAX:
        _COMPILE_CACHE[expr] = run
    return run
