"""Convenience constructors for text-document vector indexes
(reference ``stdlib/indexing/vector_document_index.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import (
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    DataIndex,
    TantivyBM25Factory,
    UsearchKnnFactory,
)

__all__ = [
    "VectorDocumentIndex",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_usearch_knn_document_index",
    "default_full_text_document_index",
]


def VectorDocumentIndex(  # noqa: N802 — reference-compatible name
    data_column: ColumnReference,
    data_table: Table,
    embedder: Any,
    *,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
    metric: str = BruteForceKnnMetricKind.COS,
    reserved_space: int = 1024,
    mesh: Any = None,
) -> DataIndex:
    factory = BruteForceKnnFactory(
        dimensions=dimensions,
        reserved_space=reserved_space,
        metric=metric,
        embedder=embedder,
        mesh=mesh,
    )
    return factory.build_data_index(data_column, data_table, metadata_column)


default_vector_document_index = VectorDocumentIndex
default_brute_force_knn_document_index = VectorDocumentIndex


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    embedder: Any,
    *,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
    metric: str = BruteForceKnnMetricKind.COS,
    reserved_space: int = 1024,
) -> DataIndex:
    factory = UsearchKnnFactory(
        dimensions=dimensions,
        reserved_space=reserved_space,
        metric=metric,
        embedder=embedder,
    )
    return factory.build_data_index(data_column, data_table, metadata_column)


def default_full_text_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    return TantivyBM25Factory().build_data_index(
        data_column, data_table, metadata_column
    )
