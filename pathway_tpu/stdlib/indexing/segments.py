"""Segmented online index maintenance: delta segment + background merge.

EdgeRAG-style online indexing (PAPERS.md) for the live-churn workload:
continuous upserts *and* deletions with bounded recall loss, while the
heavy index stays mostly sealed.  :class:`SegmentedIndex` fronts any
``(key, vector)`` index (host HNSW, device sharded slab, device IVF)
with

- a mutable **delta segment** — a host dict of the most recent upserts,
  searched exactly and merged with main-segment results, so a fresh
  upsert is visible to the very next query without touching the sealed
  main index;
- a **tombstone set shared across segments** — deletions mask the main
  (and, mid-merge, the frozen) segment instead of mutating it; removing
  an absent key is a no-op;
- a **background merge** that freezes the delta + tombstones and
  compacts them into the main segment off the query path, either by
  rebuilding a fresh main (graph indexes: ``merge_strategy =
  "rebuild"``) or by applying remove+upsert in place (device slabs:
  ``"inplace"``).

Consistency: segment bookkeeping (delta, tombstones, freeze, commit)
happens under ``self._lock``; a query snapshots the delta view and mask
under it, then runs the main-segment search and the delta scan OFF the
lock — so queries don't serialize on the segment and updates or
checkpoints never queue behind a graph walk or device dispatch.
In-place main mutation (bulk load, inplace merge, restore) excludes
searchers via a second ``_main_mutex``; rebuild merges swap ``main``
atomically, which the snapshot tolerates.  A query — and a checkpoint's
:meth:`state_dict` — therefore observes either the pre-merge or the
post-merge segmentation, never a torn mix; a key deleted mid-merge is
filtered from the frozen delta everywhere (search, checkpoint, merge
fold-in, rollback), so a delete is never undone by merge machinery.  A
merge interrupted by a crash loses only the merge work: the
checkpointed state is the pre-merge view, and a failed in-process merge
rolls the frozen delta/tombstones back into the live segment.

Tuning knobs (constructor args, env defaults):

- ``delta_cap`` / ``PATHWAY_INDEX_DELTA_CAP`` (1024) — delta size that
  triggers a merge; also the bulk-load threshold below which a batch
  goes through the delta instead of straight into main.
- ``tombstone_fraction`` / ``PATHWAY_INDEX_TOMBSTONE_FRACTION`` (0.25)
  — tombstones/main ratio that triggers a merge.
- ``auto_merge`` / ``PATHWAY_INDEX_AUTO_MERGE`` (1) — 0 pins merges to
  explicit :meth:`merge` calls (tests, deterministic drills).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from pathway_tpu.internals import tracing as _tracing

__all__ = ["SegmentedIndex"]


class _SegProbe:
    """In-flight search snapshot from :meth:`SegmentedIndex.dispatch`:
    the delta view + tombstone mask taken at dispatch time, plus either
    the main segment's async device handle (``probe``) or its eagerly
    computed hits (``main_hits``).  ``main is None`` marks a probe over
    an empty index.

    The probe also carries the serving layer's partial-result contract
    (``partial``/``shards_answered``/``shards_total``): a single
    SegmentedIndex is one shard that always answers authoritatively, so
    the identity coverage ``1/1`` — the multi-shard variant lives in
    :class:`pathway_tpu.serving.failover.PartitionedIndex`, whose probe
    carries the same fields with real per-shard health behind them."""

    __slots__ = (
        "queries",
        "k",
        "delta",
        "mask",
        "main",
        "probe",
        "main_hits",
        "partial",
        "shards_answered",
        "shards_total",
    )

    def __init__(self, queries, k, delta, mask, main, probe, main_hits):
        self.queries = queries
        self.k = k
        self.delta = delta
        self.mask = mask
        self.main = main
        self.probe = probe
        self.main_hits = main_hits
        self.partial = False
        self.shards_answered = 1
        self.shards_total = 1


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class SegmentedIndex:
    """Delta segment + tombstones + background merge over ``main``.

    ``main`` is any index with the repo's ``(key, vector)`` contract:
    ``add(items)``, ``remove(keys)``, ``search(queries, k)``,
    ``__len__``; ``state_dict``/``load_state_dict`` make the whole
    segmented index checkpointable, and ``export()`` (keys, matrix)
    enables rebuild-style merges.
    """

    def __init__(
        self,
        main: Any,
        *,
        delta_cap: int | None = None,
        tombstone_fraction: float | None = None,
        auto_merge: bool | None = None,
        maintenance: Any | None = None,
    ):
        self.main = main
        self.metric = getattr(main, "metric", "cos")
        self.delta_cap = max(
            1,
            delta_cap
            if delta_cap is not None
            else _env_int("PATHWAY_INDEX_DELTA_CAP", 1024),
        )
        self.tombstone_fraction = (
            tombstone_fraction
            if tombstone_fraction is not None
            else _env_float("PATHWAY_INDEX_TOMBSTONE_FRACTION", 0.25)
        )
        self.auto_merge = (
            auto_merge
            if auto_merge is not None
            else _env_int("PATHWAY_INDEX_AUTO_MERGE", 1) != 0
        )
        self._lock = threading.RLock()
        # excludes in-place main mutation (bulk load, inplace merge,
        # restore) from searchers, which run main.search off `_lock`;
        # always acquired INSIDE `_lock`, never the other way around
        self._main_mutex = threading.Lock()
        # live segment membership (authoritative: main ∪ delta − tombs)
        self._keys: set[Any] = set(self._main_keys())
        self._delta: dict[Any, np.ndarray] = {}
        self._tombs: set[Any] = set()
        # frozen mid-merge snapshot (empty unless a merge is in flight)
        self._frozen: dict[Any, np.ndarray] = {}
        self._frozen_tombs: set[Any] = set()
        self._merging = False
        self.merges_total = 0
        self.merge_failures = 0
        #: speculative-probe accounting (serving lookahead retrieval):
        #: probes fired via :meth:`dispatch`, and probes whose device
        #: handle went stale (index restored mid-flight) and were
        #: recovered by re-running the search
        self.probes_dispatched = 0
        self.probes_recovered = 0
        self._maintenance = maintenance

    # ---------------------------------------------------------------- helpers

    def _main_keys(self) -> Iterable[Any]:
        keys = getattr(self.main, "keys", None)
        if callable(keys):  # method (hnsw, ivf)
            return keys()
        if keys is not None:  # property returning a list (sharded slab)
            return keys
        return ()

    def _prep(self, vecs: np.ndarray) -> np.ndarray:
        vecs = np.ascontiguousarray(np.atleast_2d(vecs), np.float32)
        if self.metric == "cos":
            norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs / np.maximum(norms, 1e-12)
        return vecs

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._keys

    def keys(self) -> list:
        with self._lock:
            return list(self._keys)

    # ---------------------------------------------------------------- updates

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        """Upsert ``(key, vector)`` pairs into the delta segment.

        A batch at least ``delta_cap`` large with nothing buffered is a
        bulk load and goes straight into the sealed main segment — the
        initial corpus shouldn't crawl through the delta."""
        if not items:
            return
        with self._lock:
            if (
                len(items) >= self.delta_cap
                and not self._delta
                and not self._tombs
                and not self._merging
            ):
                with self._main_mutex:
                    self.main.add(list(items))
                self._keys = set(self._main_keys())
                return
            for key, vec in items:
                self._tombs.discard(key)
                self._delta[key] = self._prep(np.asarray(vec, np.float32))[0]
                self._keys.add(key)
            self._maybe_merge_locked()

    def remove(self, keys: Sequence[Any]) -> None:
        """Delete keys; an absent key is a no-op.  Keys living in the
        main (or frozen) segment are tombstoned, not physically removed —
        the merge reclaims them."""
        with self._lock:
            for key in keys:
                if key in self._delta:
                    del self._delta[key]
                    # the key may ALSO live in main/frozen under an older
                    # value — tombstone unless the delta held the only copy
                    if key in self._keys and (
                        key in self._frozen or self._has_in_main(key)
                    ):
                        self._tombs.add(key)
                elif key in self._keys:
                    self._tombs.add(key)
                self._keys.discard(key)
            self._maybe_merge_locked()

    def _has_in_main(self, key: Any) -> bool:
        has = getattr(self.main, "__contains__", None)
        if has is not None:
            try:
                return key in self.main
            except TypeError:
                pass
        return True  # conservative: a stray tombstone is a later no-op

    # ----------------------------------------------------------------- search

    def search(self, queries: np.ndarray, k: int) -> list[list[tuple[Any, float]]]:
        """Top-k per query, ``[(key, score), ...]``, higher = closer.

        Precedence per key: live delta > frozen delta > main; tombstones
        mask the older segments.  Scores are computed in the same metric
        space for every segment, so the cross-segment merge is a plain
        sort.  Implemented as an immediate dispatch + collect pair, so
        the synchronous path and the serving lookahead path share one
        snapshot/merge discipline."""
        return self.collect(self.dispatch(queries, k))

    def dispatch(self, queries: np.ndarray, k: int) -> "_SegProbe":
        """Fire a search probe and return a handle for :meth:`collect`.

        The segment view (delta + tombstone mask) is snapshotted under
        ``_lock``; the main-segment probe then launches OFF the lock, so
        upserts, deletes and checkpoints never queue behind a graph walk
        or device dispatch, and queries don't serialize on the segment.
        This is safe because ``self.main`` only changes by atomic
        pointer swap at a rebuild commit (the snapshot tolerates that),
        in-place main mutation (bulk load, inplace merge, restore)
        excludes probes via ``_main_mutex``, and every key such a
        mutation touches is covered by the snapshotted delta/mask —
        either the pre- or post-merge main yields the same merged
        result.

        When the main segment supports async device probes
        (``main.dispatch``, e.g. the sharded slab), only the launch
        happens here — the device computes while the caller does other
        work and :meth:`collect` pays the host sync (TeleRAG-style
        lookahead retrieval).  Host-only main segments run their search
        eagerly on the dispatching thread instead, which preserves the
        same overlap for a serving loop whose dispatch and collect run
        on different stages."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        with self._lock:
            if not self._keys:
                return _SegProbe(queries, 0, {}, set(), None, None, None)
            k = min(k, len(self._keys))
            delta = self._delta_view_locked()
            # main results to drop: deleted keys + keys shadowed by delta
            mask = set(delta)
            mask.update(self._tombs)
            mask.update(self._frozen_tombs)
            main = self.main
            n_main = len(main)
        probe = None
        main_hits: list[list[tuple[Any, float]]] | None = None
        if n_main:
            fetch = min(k + len(mask), n_main)
            main_dispatch = getattr(main, "dispatch", None)
            if main_dispatch is not None:
                t0_ns = _tracing.now_ns()
                with self._main_mutex:
                    probe = main_dispatch(queries, fetch)
                _tracing.record_span("dispatch_segments", t0_ns, _tracing.now_ns())
                with self._lock:
                    self.probes_dispatched += 1
            elif getattr(main, "concurrent_search", False):
                main_hits = main.search(queries, fetch)
            else:
                with self._main_mutex:
                    main_hits = main.search(queries, fetch)
        return _SegProbe(queries, k, delta, mask, main, probe, main_hits)

    def collect(self, handle: "_SegProbe") -> list[list[tuple[Any, float]]]:
        """Resolve a :meth:`dispatch` handle to merged top-k results.

        A device probe whose handle went stale (the index was restored
        via ``load_state_dict`` while it was in flight) is recovered by
        re-running the full search against the restored index — the
        caller sees current results, never an exception or wrong keys."""
        queries, k = handle.queries, handle.k
        if handle.main is None:
            return [[] for _ in range(queries.shape[0])]
        main_hits = handle.main_hits
        if main_hits is None and handle.probe is not None:
            try:
                t0_ns = _tracing.now_ns()
                main_hits = handle.main.collect(handle.probe)
                _tracing.record_span("collect_segments", t0_ns, _tracing.now_ns())
            except RuntimeError:
                with self._lock:
                    self.probes_recovered += 1
                return self.search(queries, k)
        if main_hits is None:
            main_hits = [[] for _ in range(queries.shape[0])]
        delta_hits = self._search_delta(queries, handle.delta, k)
        mask = handle.mask
        out: list[list[tuple[Any, float]]] = []
        for qi in range(queries.shape[0]):
            merged = [
                (key, s) for key, s in main_hits[qi] if key not in mask
            ]
            merged.extend(delta_hits[qi])
            merged.sort(key=lambda kv: (-kv[1], str(kv[0])))
            out.append(merged[:k])
        return out

    def _delta_view_locked(self) -> dict[Any, np.ndarray]:
        """Combined delta: frozen entries shadowed by live ones.  A key
        deleted AFTER the freeze sits in ``_tombs`` and its frozen copy
        must not resurface through this view (the live ``_delta`` is
        always disjoint from ``_tombs``, so the filter only ever drops
        stale frozen entries)."""
        if not self._frozen:
            return dict(self._delta)
        view = {
            key: vec
            for key, vec in self._frozen.items()
            if key not in self._tombs
        }
        view.update(self._delta)
        return view

    def _search_delta(
        self, queries: np.ndarray, delta: dict[Any, np.ndarray], k: int
    ) -> list[list[tuple[Any, float]]]:
        if not delta:
            return [[] for _ in range(queries.shape[0])]
        keys = list(delta.keys())
        mat = np.stack([delta[key] for key in keys])
        q = self._prep(queries)
        if self.metric == "l2sq":
            scores = -(((q[:, None, :] - mat[None, :, :]) ** 2).sum(-1))
        else:
            scores = q @ mat.T
        out = []
        top_n = min(k, len(keys))
        for row in scores:
            top = np.argsort(-row)[:top_n]
            out.append([(keys[i], float(row[i])) for i in top])
        return out

    # ------------------------------------------------------------------ merge

    def _maybe_merge_locked(self) -> None:
        if not self.auto_merge or self._merging:
            return
        due = len(self._delta) >= self.delta_cap or (
            len(self._tombs) >= 16
            and len(self._tombs)
            >= self.tombstone_fraction * max(len(self.main), 1)
        )
        if due:
            self._schedule_merge()

    def _schedule_merge(self) -> None:
        m = self._maintenance
        if m is None:
            from pathway_tpu.internals.resilience import BackgroundMaintenance

            m = self._maintenance = BackgroundMaintenance()
        m.submit(self._run_merge)

    def merge(self, wait: bool = True) -> None:
        """Trigger a merge now.  ``wait=False`` hands it to the
        maintenance thread and returns immediately."""
        if wait:
            self._run_merge()
            m = self._maintenance
            if m is not None:  # a concurrent background merge may hold it
                m.drain()
        else:
            self._schedule_merge()

    def _run_merge(self) -> None:
        with self._lock:
            if self._merging or (not self._delta and not self._tombs):
                return
            self._merging = True
            self._frozen, self._delta = self._delta, {}
            self._frozen_tombs, self._tombs = self._tombs, set()
        try:
            strategy = getattr(self.main, "merge_strategy", "inplace")
            if strategy == "rebuild":
                self._merge_rebuild()
            else:
                self._merge_inplace()
        except BaseException:
            with self._lock:  # full rollback: frozen back into live
                self.merge_failures += 1
                frozen, self._frozen = self._frozen, {}
                ftombs, self._frozen_tombs = self._frozen_tombs, set()
                # keys deleted after the freeze stay deleted: their
                # frozen copies must not ride the rollback back to life
                frozen = {
                    key: vec
                    for key, vec in frozen.items()
                    if key not in self._tombs
                }
                frozen.update(self._delta)  # post-freeze upserts win
                self._delta = frozen
                self._tombs |= {t for t in ftombs if t not in self._delta}
                self._merging = False
            raise

    def _pre_commit(self) -> None:
        """Chaos hook: the instant between a finished merge and its
        atomic commit (``testing/chaos.py kill_worker_mid_merge``)."""

    def _commit_locked(self) -> None:
        self._frozen = {}
        self._frozen_tombs = set()
        self._merging = False
        self.merges_total += 1
        try:
            from pathway_tpu.internals.telemetry import get_telemetry

            get_telemetry().counter("index.merges")
        except Exception:  # noqa: BLE001
            pass

    def _frozen_survivors_locked(self) -> dict[Any, np.ndarray]:
        """Frozen-delta entries that still belong in main: a key deleted
        after the freeze (now in ``_tombs``) must not be folded back in,
        or the delete would be undone once its tombstone is discarded."""
        return {
            key: vec
            for key, vec in self._frozen.items()
            if key not in self._tombs and key not in self._frozen_tombs
        }

    def _merge_rebuild(self) -> None:
        """Build a fresh main from survivors + frozen delta off-lock,
        then pointer-swap.  Doubles as compaction for graph indexes."""
        old = self.main
        with self._lock:
            # `_frozen`/`_frozen_tombs` are only touched by this merge,
            # but `_tombs` absorbs concurrent deletes — snapshot the
            # survivor set under the lock.  A delete landing after this
            # snapshot leaves its key in the new main AND in `_tombs`:
            # still masked from every query, reclaimed next merge.
            frozen = self._frozen_survivors_locked()
            drop = set(self._frozen_tombs) | set(self._frozen)
        keys, mat = old.export()
        new = old.fresh()
        survivors = [i for i, key in enumerate(keys) if key not in drop]
        items: list[tuple[Any, Any]] = [(keys[i], mat[i]) for i in survivors]
        items.extend(frozen.items())
        for i in range(0, len(items), 4096):
            new.add(items[i : i + 4096])
        with self._lock:
            self._pre_commit()
            self.main = new
            self._commit_locked()

    def _merge_inplace(self) -> None:
        """Apply frozen tombstones + delta to the device slab.  The lock
        is held across remove+add: both are cheap host-side dispatches,
        and holding it keeps a concurrent checkpoint from seeing the
        removed-but-not-yet-upserted gap (searchers are excluded by
        ``_main_mutex`` and their snapshotted delta/mask covers every
        key touched here)."""
        with self._lock:
            dead = [t for t in self._frozen_tombs if self._has_in_main(t)]
            frozen = self._frozen_survivors_locked()
            with self._main_mutex:
                if dead:
                    self.main.remove(dead)
                if frozen:
                    self.main.add(list(frozen.items()))
            self._pre_commit()
            self._commit_locked()

    # ------------------------------------------------------------ persistence

    def state_dict(self) -> dict:
        """Snapshot-consistent state: taken under the segment lock, so a
        checkpoint racing a merge serializes the pre-merge view (frozen
        folded back into the delta) — a crash mid-merge restores cleanly
        and the merge simply re-runs after replay."""
        with self._lock:
            # the tombstone-filtered view: a key deleted after the
            # freeze must serialize as deleted, not in delta_keys AND
            # tombstones at once (loading such a state, then merging,
            # would re-insert the frozen vector while discarding the
            # tombstone — permanently resurrecting the deleted doc)
            delta = self._delta_view_locked()
            tombs = set(self._tombs) | {
                t for t in self._frozen_tombs if t not in delta
            }
            keys = list(delta.keys())
            return {
                "kind": "segmented",
                "main": self.main.state_dict(),
                "delta_keys": keys,
                "delta_vectors": np.stack([delta[key] for key in keys])
                if keys
                else np.zeros((0, 0), np.float32),
                "tombstones": list(tombs),
                "merges_total": self.merges_total,
            }

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            with self._main_mutex:
                self.main.load_state_dict(state["main"])
            vecs = np.asarray(state["delta_vectors"], np.float32)
            tombs = set(state["tombstones"])
            # a checkpoint from before the delta-view fix could carry a
            # key in both delta_keys and tombstones; the delete wins
            self._delta = {
                key: vecs[i]
                for i, key in enumerate(state["delta_keys"])
                if key not in tombs
            }
            self._tombs = tombs
            self._frozen = {}
            self._frozen_tombs = set()
            self._merging = False
            self.merges_total = int(state.get("merges_total", 0))
            self._keys = (set(self._main_keys()) | set(self._delta)) - self._tombs

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._keys),
                "main_size": len(self.main),
                "delta_size": len(self._delta) + len(self._frozen),
                "tombstones": len(self._tombs) + len(self._frozen_tombs),
                "merges_total": self.merges_total,
                "merge_failures": self.merge_failures,
                "merging": self._merging,
                "probes_dispatched": self.probes_dispatched,
                "probes_recovered": self.probes_recovered,
            }

    def close(self) -> None:
        m = self._maintenance
        if m is not None:
            m.close()
