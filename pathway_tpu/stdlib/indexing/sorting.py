"""Sorting-index helpers (reference ``stdlib/indexing/sorting.py``).

The engine's :class:`~pathway_tpu.engine.graph.SortNode` maintains
prev/next pointers per row (reference ``prev_next.rs``); this module adds
the nearest-non-None value retrieval used by ``statistical.interpolate``.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.engine.stream import Update, consolidate, per_key_changes
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = ["retrieve_prev_next_values"]


class _PrevNextValueNode(eg.Node):
    """For each row of a prev/next-linked list, the NEAREST non-None value
    in each direction (walks the pointer chain host-side; dirty epochs
    recompute the affected chains)."""

    def __init__(self, graph, input: eg.Node, prev_idx: int, next_idx: int, value_idx: int, name="prev_next_values"):
        super().__init__(graph, [input], name)
        self.prev_idx = prev_idx
        self.next_idx = next_idx
        self.value_idx = value_idx

    def make_state(self):
        return {"rows": {}, "out": {}}

    def _nearest(self, rows: dict, key: Any, direction_idx: int) -> Any:
        seen = set()
        cur = rows.get(key)
        while cur is not None:
            nxt_key = cur[direction_idx]
            if nxt_key is None or nxt_key in seen:
                return None
            seen.add(nxt_key)
            cur = rows.get(nxt_key)
            if cur is None:
                return None
            v = cur[self.value_idx]
            if v is not None:
                return v
        return None

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        rows = st["rows"]
        touched = per_key_changes(consolidate(inbatches[0]))
        if not touched:
            return []
        for key, (rem, add) in touched.items():
            if add:
                rows[key] = add[-1]
            elif rem:
                rows.pop(key, None)
        # pointer chains shift arbitrarily on insert; recompute all rows and
        # emit only the diffs (interpolate-scale tables)
        out: list[Update] = []
        new_out: dict = {}
        for key, values in rows.items():
            pv = self._nearest(rows, key, self.prev_idx)
            nv = self._nearest(rows, key, self.next_idx)
            new_out[key] = values + (pv, nv)
        for key, row in new_out.items():
            old = st["out"].get(key)
            if old != row:
                if old is not None:
                    out.append(Update(key, old, -1))
                out.append(Update(key, row, 1))
        for key in list(st["out"]):
            if key not in new_out:
                out.append(Update(key, st["out"][key], -1))
        st["out"] = new_out
        return consolidate(out)


def retrieve_prev_next_values(ordered_table: Table, value: Any = None) -> Table:
    """Given a table with ``prev``/``next`` pointer columns and a value
    column, return ``prev_value``/``next_value`` columns holding the
    NEAREST non-None value in each direction (reference
    ``sorting.py retrieve_prev_next_values``)."""
    if value is None:
        value = ordered_table.value
    name = value._name
    cols = ordered_table._column_names
    node = _PrevNextValueNode(
        G.engine_graph,
        ordered_table._node,
        prev_idx=cols.index("prev"),
        next_idx=cols.index("next"),
        value_idx=cols.index(name),
    )
    out_cols = cols + ["prev_value", "next_value"]
    dtypes = dict(ordered_table._dtypes)
    vt = dtypes.get(name, dt.ANY)
    dtypes["prev_value"] = dt.Optional(vt)
    dtypes["next_value"] = dt.Optional(vt)
    return Table(
        node,
        out_cols,
        dtypes,
        name="prev_next_values",
        layout_token=ordered_table._layout_token,
    )
