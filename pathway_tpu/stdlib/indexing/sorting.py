"""Sorting-index helpers (reference ``stdlib/indexing/sorting.py``).

The engine's :class:`~pathway_tpu.engine.graph.SortNode` maintains
prev/next pointers per row (reference ``prev_next.rs``); this module adds
the value-retrieval convenience used by ``statistical.interpolate``.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table

__all__ = ["retrieve_prev_next_values"]


def retrieve_prev_next_values(
    ordered_table: Table, value: Any = None
) -> Table:
    """Given a table with ``prev``/``next`` pointer columns and a ``value``
    column, return ``prev_value``/``next_value`` columns holding the nearest
    non-None value in each direction (reference
    ``sorting.py retrieve_prev_next_values``)."""
    import pathway_tpu as pw

    if value is None:
        value = ordered_table.value
    name = value._name

    prev_rows = ordered_table.ix(ordered_table.prev, optional=True)
    next_rows = ordered_table.ix(ordered_table.next, optional=True)
    return ordered_table.select(
        *[ordered_table[c] for c in ordered_table._column_names],
        prev_value=prev_rows[name],
        next_value=next_rows[name],
    )
