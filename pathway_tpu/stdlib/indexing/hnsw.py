"""Host HNSW graph ANN index (the reference's usearch role,
``src/external_integration/usearch_integration.rs:1-163``).

The graph walk is pointer-chasing — hostile to XLA — so like the
reference this index lives on the host: the C++ implementation in
``native/pathway_native.cpp`` (``hnsw_*``), fronted here by a key-mapped
wrapper with the same ``(key, vector)`` contract as
:class:`~pathway_tpu.parallel.ShardedKnnIndex`.  Without the native
module it degrades to exact brute force (numpy), which is slower but
identical in results.

Scores follow the repo convention (higher = closer): ``cos``/``dot``
return the inner product; ``l2sq`` the negated squared distance.

Removal tombstones graph slots rather than unlinking them, so
long-running churn walks over dead entries; once the dead fraction
passes ``tombstone_fraction`` the index compacts itself by rebuilding
the graph from the host-side vector store.  The same store backs
``state_dict``/``load_state_dict`` (checkpoint restore) and
``export``/``fresh`` (segment merges, see
:class:`~pathway_tpu.stdlib.indexing.segments.SegmentedIndex`).
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

import numpy as np

from pathway_tpu.internals import native as _native

__all__ = ["HnswIndex"]

_COMPACT_MIN_SLOTS = 64
_CHUNK = 4096


class HnswIndex:
    """(key, vector) ANN index with live add/remove."""

    # segment merges rebuild a fresh graph rather than editing in place
    merge_strategy = "rebuild"
    # concurrent search/search and search/add are safe: the native graph
    # serializes on its own mutex (GIL released), compact/load swap the
    # (handle, key map) pair atomically against the snapshot below, and
    # the slot decode tolerates concurrent remove()s — so SegmentedIndex
    # lets queries hit this main without serializing on _main_mutex
    concurrent_search = True

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        M: int = 16,
        ef_construction: int = 128,
        ef_search: int = 64,
        tombstone_fraction: float = 0.33,
    ):
        if metric not in ("cos", "dot", "l2sq"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dim = dim
        self.metric = metric
        self.M = M
        self.ef_construction = ef_construction
        self.ef_search = ef_search
        self.tombstone_fraction = tombstone_fraction
        self._slot_of: dict[Any, int] = {}
        self._key_of: dict[int, Any] = {}
        # host copy of every live vector (already ``_prep``-ed): feeds
        # the exact fallback, compaction rebuilds, and state_dict
        self._store: dict[Any, np.ndarray] = {}
        self._hw = 0  # native slot high-water mark (live + tombstoned)
        self.compactions = 0
        self._lock = threading.RLock()
        native = _native.load()
        if native is not None and hasattr(native, "hnsw_new"):
            self._native = native
            self._h = native.hnsw_new(
                dim, M, ef_construction, 1 if metric == "l2sq" else 0
            )
        else:  # exact fallback: same results, no graph
            self._native = None

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: Any) -> bool:
        return key in self._store

    def keys(self) -> list:
        return list(self._store)

    def _prep(self, vecs: np.ndarray) -> np.ndarray:
        vecs = np.ascontiguousarray(vecs, np.float32)
        if self.metric == "cos":
            norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs / np.maximum(norms, 1e-12)
        return vecs

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        if not items:
            return
        # upsert semantics: last occurrence of a key wins — dedup WITHIN
        # the batch too, or the earlier duplicate's slot would stay alive
        # (and keep surfacing in results) with no key mapping back to it
        last: dict[Any, Any] = {}
        for k, v in items:
            last[k] = v
        items = list(last.items())
        keys = [k for k, _ in items]
        mat = self._prep(np.stack([np.asarray(v, np.float32) for _, v in items]))
        with self._lock:
            # re-adding a key replaces its vector
            stale = [k for k in keys if k in self._slot_of]
            if stale:
                self.remove(stale)
            self._insert_prepped(keys, mat)

    def _insert_prepped(self, keys: list, mat: np.ndarray) -> None:
        for key, row in zip(keys, mat):
            self._store[key] = row
        if self._native is None:
            return
        slots = self._native.hnsw_add(self._h, mat)
        for key, slot in zip(keys, slots):
            self._slot_of[key] = slot
            self._key_of[slot] = key
            if slot >= self._hw:
                self._hw = slot + 1

    def remove(self, keys: Sequence[Any]) -> None:
        """Remove keys; absent keys are a no-op (churn replay sends
        deletes for rows that never made the checkpoint)."""
        with self._lock:
            if self._native is None:
                for k in keys:
                    self._store.pop(k, None)
                return
            slots = []
            for k in keys:
                s = self._slot_of.pop(k, None)
                if s is not None:
                    self._key_of.pop(s, None)
                    self._store.pop(k, None)
                    slots.append(s)
            if slots:
                self._native.hnsw_remove(self._h, slots)
            dead = self._hw - len(self._slot_of)
            if (
                self._hw >= _COMPACT_MIN_SLOTS
                and dead > self.tombstone_fraction * self._hw
            ):
                self.compact()

    def compact(self) -> None:
        """Rebuild the native graph from live vectors, reclaiming
        tombstoned slots (satellite: unbounded tombstone growth)."""
        if self._native is None:
            return
        with self._lock:
            keys = list(self._store.keys())
            h = self._native.hnsw_new(
                self.dim, self.M, self.ef_construction,
                1 if self.metric == "l2sq" else 0,
            )
            slot_of: dict[Any, int] = {}
            key_of: dict[int, Any] = {}
            hw = 0
            for i in range(0, len(keys), _CHUNK):
                chunk = keys[i : i + _CHUNK]
                mat = np.stack([self._store[k] for k in chunk])
                slots = self._native.hnsw_add(h, np.ascontiguousarray(mat))
                for key, slot in zip(chunk, slots):
                    slot_of[key] = slot
                    key_of[slot] = key
                    if slot >= hw:
                        hw = slot + 1
            # atomic swap: a concurrent search snapshots the old pair
            self._h, self._slot_of, self._key_of, self._hw = (
                h, slot_of, key_of, hw,
            )
            self.compactions += 1

    def search(
        self, queries: np.ndarray, k: int
    ) -> list[list[tuple[Any, float]]]:
        """Top-k per query as [(key, score), ...], score higher = closer."""
        queries = self._prep(np.atleast_2d(np.asarray(queries, np.float32)))
        n = len(self)
        if n == 0:
            return [[] for _ in range(queries.shape[0])]
        k = min(k, n)
        if self._native is None:
            return self._search_exact(queries, k)
        with self._lock:  # consistent (handle, key map) pair vs compact()
            h, key_of = self._h, self._key_of
        ef = max(self.ef_search, k)
        raw = self._native.hnsw_search(h, queries, k, ef)
        # adaptive retry: heavy tombstone churn can starve survivors
        while any(len(ids) < k for ids, _ in raw) and ef < 4 * n:
            ef *= 4
            raw = self._native.hnsw_search(h, queries, k, ef)
        out: list[list[tuple[Any, float]]] = []
        for ids, dists in raw:
            # native distance is -dot (ip) or l2sq; both negate into the
            # higher-is-closer score convention.  remove() pops entries
            # from the shared key map in place, so decode with .get: a
            # slot deleted mid-search drops out instead of raising.
            row: list[tuple[Any, float]] = []
            for s, d in zip(ids, dists):
                key = key_of.get(s)
                if key is not None:
                    row.append((key, -d))
            out.append(row)
        return out

    def _search_exact(self, q: np.ndarray, k: int) -> list[list[tuple[Any, float]]]:
        with self._lock:  # consistent snapshot vs concurrent add/remove
            keys = list(self._store.keys())
            if not keys:
                return [[] for _ in range(q.shape[0])]
            mat = np.stack([self._store[key] for key in keys])
        if self.metric == "l2sq":
            scores = -(
                ((q[:, None, :] - mat[None, :, :]) ** 2).sum(-1)
            )
        else:
            scores = q @ mat.T
        out = []
        for row in scores:
            top = np.argsort(-row)[:k]
            out.append([(keys[i], float(row[i])) for i in top])
        return out

    # ------------------------------------------------- segments / persistence

    def fresh(self) -> "HnswIndex":
        """Empty index with the same hyperparameters (merge rebuilds)."""
        return HnswIndex(
            self.dim,
            metric=self.metric,
            M=self.M,
            ef_construction=self.ef_construction,
            ef_search=self.ef_search,
            tombstone_fraction=self.tombstone_fraction,
        )

    def export(self) -> tuple[list, np.ndarray]:
        """(keys, matrix) of live vectors, already normalized."""
        with self._lock:
            keys = list(self._store.keys())
            mat = (
                np.stack([self._store[k] for k in keys])
                if keys
                else np.zeros((0, self.dim), np.float32)
            )
        return keys, mat

    def stats(self) -> dict:
        slots = self._hw if self._native is not None else len(self._store)
        return {
            "size": len(self._store),
            "slots": slots,
            "tombstones": max(0, slots - len(self._store)),
            "compactions": self.compactions,
        }

    def state_dict(self) -> dict:
        """Host arrays only (picklable through the checkpoint writer);
        the graph itself is rebuilt on load — insertion is the cost of
        restore, but no native memory layout leaks into snapshots."""
        keys, mat = self.export()
        return {
            "kind": "hnsw",
            "dim": self.dim,
            "metric": self.metric,
            "M": self.M,
            "ef_construction": self.ef_construction,
            "ef_search": self.ef_search,
            "keys": keys,
            "vectors": mat,
        }

    def load_state_dict(self, state: dict) -> None:
        if state.get("dim", self.dim) != self.dim or state.get(
            "metric", self.metric
        ) != self.metric:
            raise ValueError("state_dict does not match index configuration")
        keys = list(state["keys"])
        mat = np.ascontiguousarray(np.asarray(state["vectors"], np.float32))
        with self._lock:
            self._store = {}
            self._slot_of = {}
            self._key_of = {}
            self._hw = 0
            if self._native is not None:
                self._h = self._native.hnsw_new(
                    self.dim, self.M, self.ef_construction,
                    1 if self.metric == "l2sq" else 0,
                )
            for i in range(0, len(keys), _CHUNK):
                self._insert_prepped(
                    keys[i : i + _CHUNK],
                    np.ascontiguousarray(mat[i : i + _CHUNK]),
                )
