"""Host HNSW graph ANN index (the reference's usearch role,
``src/external_integration/usearch_integration.rs:1-163``).

The graph walk is pointer-chasing — hostile to XLA — so like the
reference this index lives on the host: the C++ implementation in
``native/pathway_native.cpp`` (``hnsw_*``), fronted here by a key-mapped
wrapper with the same ``(key, vector)`` contract as
:class:`~pathway_tpu.parallel.ShardedKnnIndex`.  Without the native
module it degrades to exact brute force (numpy), which is slower but
identical in results.

Scores follow the repo convention (higher = closer): ``cos``/``dot``
return the inner product; ``l2sq`` the negated squared distance.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pathway_tpu.internals import native as _native

__all__ = ["HnswIndex"]


class HnswIndex:
    """(key, vector) ANN index with live add/remove."""

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        M: int = 16,
        ef_construction: int = 128,
        ef_search: int = 64,
    ):
        if metric not in ("cos", "dot", "l2sq"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dim = dim
        self.metric = metric
        self.M = M
        self.ef_construction = ef_construction
        self.ef_search = ef_search
        self._slot_of: dict[Any, int] = {}
        self._key_of: dict[int, Any] = {}
        native = _native.load()
        if native is not None and hasattr(native, "hnsw_new"):
            self._native = native
            self._h = native.hnsw_new(
                dim, M, ef_construction, 1 if metric == "l2sq" else 0
            )
        else:  # exact fallback: same results, no graph
            self._native = None
            self._vecs: dict[Any, np.ndarray] = {}

    def __len__(self) -> int:
        if self._native is None:
            return len(self._vecs)
        return self._native.hnsw_len(self._h)

    def _prep(self, vecs: np.ndarray) -> np.ndarray:
        vecs = np.ascontiguousarray(vecs, np.float32)
        if self.metric == "cos":
            norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs / np.maximum(norms, 1e-12)
        return vecs

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        if not items:
            return
        # upsert semantics: last occurrence of a key wins — dedup WITHIN
        # the batch too, or the earlier duplicate's slot would stay alive
        # (and keep surfacing in results) with no key mapping back to it
        last: dict[Any, Any] = {}
        for k, v in items:
            last[k] = v
        items = list(last.items())
        # re-adding a key replaces its vector
        stale = [k for k, _ in items if k in self._slot_of]
        if stale:
            self.remove(stale)
        keys = [k for k, _ in items]
        mat = self._prep(np.stack([np.asarray(v, np.float32) for _, v in items]))
        if self._native is None:
            for key, row in zip(keys, mat):
                self._vecs[key] = row
            return
        slots = self._native.hnsw_add(self._h, mat)
        for key, slot in zip(keys, slots):
            self._slot_of[key] = slot
            self._key_of[slot] = key

    def remove(self, keys: Sequence[Any]) -> None:
        if self._native is None:
            for k in keys:
                self._vecs.pop(k, None)
            return
        slots = []
        for k in keys:
            s = self._slot_of.pop(k, None)
            if s is not None:
                self._key_of.pop(s, None)
                slots.append(s)
        if slots:
            self._native.hnsw_remove(self._h, slots)

    def search(
        self, queries: np.ndarray, k: int
    ) -> list[list[tuple[Any, float]]]:
        """Top-k per query as [(key, score), ...], score higher = closer."""
        queries = self._prep(np.atleast_2d(np.asarray(queries, np.float32)))
        n = len(self)
        if n == 0:
            return [[] for _ in range(queries.shape[0])]
        k = min(k, n)
        if self._native is None:
            return self._search_exact(queries, k)
        ef = max(self.ef_search, k)
        raw = self._native.hnsw_search(self._h, queries, k, ef)
        # adaptive retry: heavy tombstone churn can starve survivors
        while any(len(ids) < k for ids, _ in raw) and ef < 4 * n:
            ef *= 4
            raw = self._native.hnsw_search(self._h, queries, k, ef)
        out: list[list[tuple[Any, float]]] = []
        for ids, dists in raw:
            # native distance is -dot (ip) or l2sq; both negate into the
            # higher-is-closer score convention
            out.append(
                [
                    (self._key_of[s], -d)
                    for s, d in zip(ids, dists)
                    if s in self._key_of
                ]
            )
        return out

    def _search_exact(self, q: np.ndarray, k: int) -> list[list[tuple[Any, float]]]:
        keys = list(self._vecs.keys())
        mat = np.stack([self._vecs[key] for key in keys])
        if self.metric == "l2sq":
            scores = -(
                ((q[:, None, :] - mat[None, :, :]) ** 2).sum(-1)
            )
        else:
            scores = q @ mat.T
        out = []
        for row in scores:
            top = np.argsort(-row)[:k]
            out.append([(keys[i], float(row[i])) for i in top])
        return out

# NOTE: no state_dict — external-index adapters are rebuilt from replayed
# input on recovery (engine/external_index.py keeps docs in operator
# state; the adapter is reconstructed, never pickled).
