"""stdlib: algorithms written against the Table API (reference
``python/pathway/stdlib/``): temporal, indexing, ml, graphs, stateful,
statistical, ordered, utils, viz."""

from typing import Any


def __getattr__(name: str) -> Any:
    import importlib

    if name in (
        "temporal",
        "indexing",
        "ml",
        "graphs",
        "stateful",
        "statistical",
        "ordered",
        "utils",
        "viz",
    ):
        return importlib.import_module(f"pathway_tpu.stdlib.{name}")
    raise AttributeError(name)
