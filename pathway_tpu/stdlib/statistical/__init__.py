"""Statistical helpers (reference ``python/pathway/stdlib/statistical/``)."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = ["interpolate", "InterpolateMode"]


class InterpolateMode:
    LINEAR = "linear"


def interpolate(
    self: Table, timestamp: Any, *values: Any, mode: str = InterpolateMode.LINEAR
) -> Table:
    """Linear interpolation of missing (None) values over time order
    (reference ``stdlib/statistical/_interpolate.py``): each None cell takes
    the linear blend of the nearest non-None neighbours in timestamp order.

    Implementation: a global sorted_tuple reduce packs (ts, values..., id)
    rows; one apply computes the interpolated mapping; a constant-key ix
    broadcasts it back to every row.  Incremental per-epoch (the reduce and
    mapping recompute only when inputs change).
    """
    if mode != InterpolateMode.LINEAR:
        raise ValueError(f"unsupported interpolation mode {mode!r}")

    table = self
    ts_name = timestamp._name
    val_names = [v._name for v in values]

    packed = table.reduce(
        rows=pw.reducers.sorted_tuple(
            pw.make_tuple(table[ts_name], *[table[v] for v in val_names], table.id)
        )
    )

    def interp(rows: tuple) -> dict:
        out: dict = {}
        for vi, vname in enumerate(val_names):
            known = [(r[0], r[1 + vi]) for r in rows if r[1 + vi] is not None]
            for r in rows:
                t, key, v = r[0], r[-1], r[1 + vi]
                if v is None and known:
                    before = [(kt, kv) for kt, kv in known if kt <= t]
                    after = [(kt, kv) for kt, kv in known if kt >= t]
                    if before and after:
                        (t0, v0), (t1, v1) = before[-1], after[0]
                        v = v0 if t1 == t0 else v0 + (v1 - v0) * (t - t0) / (t1 - t0)
                    elif before:
                        v = before[-1][1]
                    else:
                        v = after[0][1]
                out.setdefault(key, {})[vname] = v
        return out

    mapping = packed.select(m=pw.apply(interp, pw.this.rows))
    # broadcast the singleton mapping row to every input row: the global
    # reduce's key is ref_scalar() (empty group), so pointer_from() hits it
    broadcast = mapping.ix(mapping.pointer_from(), context=table)

    def pick(m: Any, key: Any, name: str) -> Any:
        if m is None or m is pw.Error:
            return None
        return m.get(key, {}).get(name)

    return table.with_columns(
        **{
            name: pw.apply(pick, broadcast.m, table.id, name)
            for name in val_names
        }
    )
