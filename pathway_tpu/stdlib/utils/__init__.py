"""``pw.utils`` (reference ``python/pathway/stdlib/utils/``):
AsyncTransformer, column helpers, pandas_transformer, bucketing/filtering."""

from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.col import flatten_column, multiapply_all, unpack_col
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer

__all__ = [
    "AsyncTransformer",
    "unpack_col",
    "flatten_column",
    "multiapply_all",
    "pandas_transformer",
]
