"""Column utilities (reference ``stdlib/utils/col.py``)."""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = ["unpack_col", "flatten_column", "multiapply_all", "apply_all_rows", "groupby_reduce_majority"]


def unpack_col(column: Any, *names: Any, schema: Any = None) -> Table:
    """Expand a tuple column into separate columns (reference
    ``col.py unpack_col``)."""
    table: Table = column._table
    if schema is not None:
        names = tuple(schema.column_names())
    out = {}
    for i, n in enumerate(names):
        n = n if isinstance(n, str) else n._name
        out[n] = pw.apply(lambda t, i=i: None if t is None else t[i], column)
    return table.select(**out)


def flatten_column(column: Any, origin_id: str | None = "origin_id") -> Table:
    """One row per element of an iterable column; keeps a pointer to the
    source row (reference ``col.py flatten_column``)."""
    table: Table = column._table
    name = column._name
    with_origin = table.select(
        **{name: table[name], origin_id or "origin_id": table.id}
    )
    return with_origin.flatten(with_origin[name])


def apply_all_rows(
    *cols: Any, fun: Callable, result_col_name: str = "result"
) -> Table:
    """Apply ``fun`` to ALL rows' values at once: fun receives one list per
    column, returns a list of per-row results (reference
    ``col.py apply_all_rows``)."""
    from pathway_tpu.internals.udfs import batch_udf

    table: Table = cols[0]._table
    wrapped = batch_udf(fun)
    return table.select(**{result_col_name: wrapped(*cols)})


multiapply_all = apply_all_rows


def groupby_reduce_majority(column: Any, value_column: Any) -> Table:
    """Majority value per group (reference ``col.py groupby_reduce_majority``)."""
    table: Table = column._table
    counted = table.groupby(column, value_column).reduce(
        column, value_column, _pw_cnt=pw.reducers.count()
    )
    return (
        counted.groupby(counted[column._name])
        .reduce(
            counted[column._name],
            majority=pw.reducers.argmax(
                counted["_pw_cnt"], counted[value_column._name]
            ),
        )
    )
