"""``pw.AsyncTransformer`` — fully decoupled async row transformation
(reference ``stdlib/utils/async_transformer.py:61-400``).

Mechanism mirrors the reference's loopback: subscribe to the input
table, run ``invoke`` on an event loop with capacity/retry/cache
wrappers, and re-ingest results through a python connector.  Results
arrive at LATER epochs than their inputs (fully asynchronous); failed
rows carry ``_async_status == "-FAILURE-"`` and are dropped from
``.successful``.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    coerce_async,
    with_cache_strategy,
    with_capacity,
    with_retry_strategy,
)

__all__ = ["AsyncTransformer"]

_SUCCESS = "-SUCCESS-"
_FAILURE = "-FAILURE-"


class _LoopbackSubject:
    """The python-connector re-entry point (reference ``_AsyncConnector``).

    ``pending_count`` is the scheduler's completion protocol: the run may
    only end when it reports 0 (queued + in-flight work); the in-flight
    counter is incremented BEFORE dequeueing so the count never transiently
    dips while an item moves between the queue and a task."""

    def __init__(self, transformer: "AsyncTransformer"):
        self.transformer = transformer

    def pending_count(self) -> int:
        t = self.transformer
        return t._queue.qsize() + t._inflight

    def run(self, events: Any) -> None:
        t = self.transformer
        loop = asyncio.new_event_loop()
        t._loop = loop

        async def main() -> None:
            done = False
            while True:
                if (done or events.stopped) and t._inflight == 0 and t._queue.empty():
                    return
                t._inflight += 1
                try:
                    item = t._queue.get_nowait()
                except _queue.Empty:
                    t._inflight -= 1
                    await asyncio.sleep(0.02)
                    continue
                if item is None:
                    t._inflight -= 1
                    done = True
                    continue
                kind, key, row = item
                # per-key ordering (reference _AsyncConnector's consistency
                # buffers): each add gets a sequence number; only the LATEST
                # version of a key may emit, so a remove or re-add arriving
                # while an older invoke is in flight supersedes it
                t._seq += 1
                t._latest[key] = t._seq
                if kind == "remove":
                    cached = t._results.pop(key, None)
                    if cached is not None:
                        events.remove(key, cached)
                        events.commit()
                    t._inflight -= 1
                    continue

                async def work(key=key, row=row, myseq=t._seq) -> None:
                    try:
                        result = await t._invoke(**row)
                        if not isinstance(result, dict):
                            raise TypeError("invoke() must return a dict")
                        values = tuple(
                            result.get(c) for c in t._out_value_cols
                        ) + (_SUCCESS,)
                    except Exception:  # noqa: BLE001
                        values = tuple(None for _ in t._out_value_cols) + (_FAILURE,)
                    if t._latest.get(key) == myseq:
                        old = t._results.get(key)
                        if old is not None:
                            events.remove(key, old)
                        t._results[key] = values
                        events.add(key, values)
                        events.commit()
                    t._inflight -= 1  # AFTER the result is in the queue

                loop.create_task(work())

        loop.run_until_complete(main())


class AsyncTransformer:
    """Subclass and define ``async def invoke(self, **row) -> dict``
    returning values for ``output_schema`` (reference ``:282``)."""

    output_schema: sch.SchemaMetaclass | None = None

    def __init__(
        self,
        input_table: Table,
        *,
        instance: Any = None,
        autocommit_duration_ms: int | None = 100,
    ):
        assert self.output_schema is not None, "set output_schema"
        self._input = input_table
        self._queue: _queue.Queue = _queue.Queue()
        self._results: dict[Any, tuple] = {}
        self._inflight = 0
        self._seq = 0
        self._latest: dict[Any, int] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._out_value_cols = list(self.output_schema.column_names())
        self._invoke = coerce_async(self.invoke)
        self._capacity: int | None = None
        self._retry: AsyncRetryStrategy | None = None
        self._cache: CacheStrategy | None = None

        cols = input_table.column_names()
        pw.io.subscribe(
            input_table,
            on_change=lambda key, row, time, is_addition: self._queue.put(
                ("add" if is_addition else "remove", key, dict(row))
            ),
            on_end=lambda: self._queue.put(None),
            name="async_transformer_in",
        )

        full_schema = sch.schema_from_columns(
            {
                **self.output_schema.columns(),
                "_async_status": sch.ColumnDefinition(name="_async_status"),
            },
            name="AsyncTransformerOutput",
        )
        from pathway_tpu.io._connector import input_table as make_input

        self._result_table = make_input(
            _LoopbackSubject(self),
            full_schema,
            name="async_transformer_out",
            auxiliary=True,
        )

    async def invoke(self, **kwargs: Any) -> dict:  # pragma: no cover
        raise NotImplementedError

    # -- composable options (reference with_options) --------------------
    def with_options(
        self,
        capacity: int | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        timeout: float | None = None,
    ) -> "AsyncTransformer":
        fun = coerce_async(self.invoke)
        if retry_strategy is not None:
            fun = with_retry_strategy(fun, retry_strategy)
        if cache_strategy is not None:
            fun = with_cache_strategy(fun, cache_strategy)
        if capacity is not None:
            fun = with_capacity(fun, capacity)
        self._invoke = fun
        return self

    # -- result tables ---------------------------------------------------
    @property
    def output_table(self) -> Table:
        return self._result_table

    @property
    def successful(self) -> Table:
        ok = self._result_table.filter(pw.this["_async_status"] == _SUCCESS)
        return ok.select(
            **{c: ok[c] for c in self._out_value_cols}
        )

    @property
    def failed(self) -> Table:
        return self._result_table.filter(pw.this["_async_status"] == _FAILURE)
