"""``pandas_transformer`` (reference ``stdlib/utils/pandas_transformer.py``):
run a pandas function over whole tables per epoch."""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table

__all__ = ["pandas_transformer"]


def pandas_transformer(
    output_schema: sch.SchemaMetaclass, output_universe: Any = None
) -> Callable:
    """Decorator: the wrapped function receives pandas DataFrames (one per
    input table) and returns a DataFrame matching ``output_schema``."""

    def wrapper(fun: Callable) -> Callable:
        def transformer(*tables: Table) -> Table:
            import pandas as pd

            first = tables[0]
            cols_list = [t._column_names for t in tables]

            def run_batch(*col_lists) -> list:
                # rebuild one DataFrame per input table
                dfs = []
                start = 0
                for t_cols in cols_list:
                    data = {
                        c: col_lists[start + i] for i, c in enumerate(t_cols)
                    }
                    dfs.append(pd.DataFrame(data))
                    start += len(t_cols)
                out_df = fun(*dfs)
                out_cols = output_schema.column_names()
                return [
                    tuple(row[c] for c in out_cols)
                    for _, row in out_df.reset_index(drop=True).iterrows()
                ]

            if len(tables) != 1:
                raise NotImplementedError(
                    "pandas_transformer currently supports one input table"
                )
            t = first
            res = t.reduce(
                _pw_rows=pw.reducers.tuple(
                    pw.apply(lambda *vs: tuple(vs), *[t[c] for c in t._column_names])
                )
            )

            def expand(rows_tuple):
                col_lists = list(zip(*rows_tuple)) if rows_tuple else [[] for _ in t._column_names]
                return run_batch(*col_lists)

            flat_src = res.select(_pw_out=pw.apply(expand, res["_pw_rows"]))
            flat = flat_src.flatten(flat_src["_pw_out"])
            out_cols = output_schema.column_names()
            return flat.select(
                **{
                    c: pw.apply(lambda r, i=i: r[i], flat["_pw_out"])
                    for i, c in enumerate(out_cols)
                }
            )

        return transformer

    return wrapper
