"""``pandas_transformer`` (reference ``stdlib/utils/pandas_transformer.py``):
run a pandas function over whole tables per epoch."""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table

__all__ = ["pandas_transformer"]


def pandas_transformer(
    output_schema: sch.SchemaMetaclass, output_universe: Any = None
) -> Callable:
    """Decorator: the wrapped function receives pandas DataFrames (one per
    input table) and returns a DataFrame matching ``output_schema``."""

    def wrapper(fun: Callable) -> Callable:
        def transformer(*tables: Table) -> Table:
            import pandas as pd

            cols_list = [t._column_names for t in tables]

            def run_batch(*col_lists) -> list:
                # rebuild one DataFrame per input table
                dfs = []
                start = 0
                for t_cols in cols_list:
                    data = {
                        c: col_lists[start + i] for i, c in enumerate(t_cols)
                    }
                    dfs.append(pd.DataFrame(data))
                    start += len(t_cols)
                out_df = fun(*dfs)
                out_cols = output_schema.column_names()
                return [
                    tuple(row[c] for c in out_cols)
                    for _, row in out_df.reset_index(drop=True).iterrows()
                ]

            # pack EVERY input table into one row of tuples, cross-join the
            # packs, and rebuild the DataFrames inside one apply
            packs = [
                t.reduce(
                    _pw_rows=pw.reducers.tuple(
                        pw.apply(
                            lambda *vs: tuple(vs), *[t[c] for c in t._column_names]
                        )
                    )
                )
                for t in tables
            ]

            def expand(*row_tuples):
                col_lists: list = []
                for t_cols, rows_tuple in zip(cols_list, row_tuples):
                    if rows_tuple:
                        col_lists.extend(list(zip(*rows_tuple)))
                    else:
                        col_lists.extend([[] for _ in t_cols])
                return run_batch(*col_lists)

            joined = packs[0].select(_pw_rows0=pw.this._pw_rows)
            for i, p in enumerate(packs[1:], start=1):
                # join_left: an EMPTY later table contributes an empty
                # DataFrame instead of wiping the whole output
                joined = joined.join_left(p).select(
                    **{
                        f"_pw_rows{j}": getattr(pw.left, f"_pw_rows{j}")
                        for j in range(i)
                    },
                    **{f"_pw_rows{i}": pw.right._pw_rows},
                )
            flat_src = joined.select(
                _pw_out=pw.apply(
                    expand,
                    *[joined[f"_pw_rows{j}"] for j in range(len(packs))],
                )
            )
            flat = flat_src.flatten(flat_src["_pw_out"])
            out_cols = output_schema.column_names()
            return flat.select(
                **{
                    c: pw.apply(lambda r, i=i: r[i], flat["_pw_out"])
                    for i, c in enumerate(out_cols)
                }
            )

        return transformer

    return wrapper
