"""``pw.viz`` (reference ``stdlib/viz/``: Bokeh/Panel live plots).

Bokeh/Panel are not available in this environment; ``table.plot`` and
``show`` degrade to a textual live view built on ``pw.io.subscribe``.
"""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = ["plot", "show", "table_viz"]


def table_viz(table: Table, sorting_col: str | None = None) -> Any:
    """Textual live widget: returns an object whose ``rows`` dict tracks
    the table (reference shows a Panel table widget)."""

    class LiveView:
        def __init__(self) -> None:
            self.rows: dict = {}

        def _repr_html_(self) -> str:
            import html

            cells = "".join(
                f"<tr>{''.join(f'<td>{html.escape(str(v))}</td>' for v in row)}</tr>"
                for row in self.rows.values()
            )
            head = "".join(f"<th>{c}</th>" for c in table._column_names)
            return f"<table><tr>{head}</tr>{cells}</table>"

    view = LiveView()

    def on_change(key, row, time, is_addition):
        if is_addition:
            view.rows[key] = tuple(row.values())
        else:
            view.rows.pop(key, None)

    pw.io.subscribe(table, on_change=on_change, name="viz")
    return view


def plot(table: Table, plotting_function: Callable | None = None, sorting_col: str | None = None) -> Any:
    try:
        import bokeh  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pw.viz.plot needs bokeh (unavailable here); use table_viz for "
            "a textual live view"
        ) from e


show = table_viz
