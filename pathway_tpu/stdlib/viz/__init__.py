"""``pw.viz`` (reference ``stdlib/viz/``: Bokeh/Panel live plots).

When Bokeh is installed, ``plot`` drives a user plotting function over a
live ColumnDataSource like the reference.  Without it (this
environment), ``plot`` still produces a REAL artifact: a live,
dependency-free SVG chart — line series per numeric column over the
sorting column — rendered through ``_repr_html_`` (notebooks), ``to_svg``
and ``save_html``.  ``table_viz``/``show`` provide the live table widget.
"""

from __future__ import annotations

import html as _html
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = ["plot", "show", "table_viz", "LivePlot"]


def table_viz(table: Table, sorting_col: str | None = None) -> Any:
    """Live table widget: ``rows`` tracks the table; renders as an HTML
    table (reference shows a Panel table widget)."""

    class LiveView:
        def __init__(self) -> None:
            self.rows: dict = {}

        def _repr_html_(self) -> str:
            cells = "".join(
                f"<tr>{''.join(f'<td>{_html.escape(str(v))}</td>' for v in row)}</tr>"
                for row in self.rows.values()
            )
            head = "".join(f"<th>{c}</th>" for c in table._column_names)
            return f"<table><tr>{head}</tr>{cells}</table>"

    view = LiveView()

    def on_change(key, row, time, is_addition):
        if is_addition:
            view.rows[key] = tuple(row.values())
        else:
            view.rows.pop(key, None)

    pw.io.subscribe(table, on_change=on_change, name="viz")
    return view


class LivePlot:
    """Continuously updated SVG chart over a table's numeric columns."""

    W, H, PAD = 640, 360, 45
    _COLORS = ["#3366cc", "#dc3912", "#109618", "#ff9900", "#990099"]

    def __init__(self, columns: list[str], x_col: str | None):
        self._columns = columns
        self._x_col = x_col
        self.rows: dict = {}

    # -- data ----------------------------------------------------------
    def _series(self) -> tuple[list, dict[str, list]]:
        rows = list(self.rows.values())
        cols = self._columns
        xi = cols.index(self._x_col) if self._x_col in cols else None
        if xi is not None:
            rows.sort(key=lambda r: (r[xi] is None, r[xi]))
            xs = [r[xi] for r in rows]
        else:
            xs = list(range(len(rows)))
        ys: dict[str, list] = {}
        for i, c in enumerate(cols):
            if i == xi:
                continue
            vals = [r[i] for r in rows]
            if all(isinstance(v, (int, float)) or v is None for v in vals) and any(
                isinstance(v, (int, float)) for v in vals
            ):
                ys[c] = vals
        return xs, ys

    # -- rendering -----------------------------------------------------
    def to_svg(self) -> str:
        xs, ys = self._series()
        W, H, P = self.W, self.H, self.PAD
        parts = [
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" height="{H}" '
            f'viewBox="0 0 {W} {H}" style="background:#fff;font-family:sans-serif">'
        ]
        parts.append(
            f'<rect x="{P}" y="{P}" width="{W - 2 * P}" height="{H - 2 * P}" '
            'fill="none" stroke="#999"/>'
        )
        numeric_x = [x for x in xs if isinstance(x, (int, float))]
        flat = [v for vs in ys.values() for v in vs if isinstance(v, (int, float))]
        if flat and (numeric_x or xs):
            if numeric_x:
                x0, x1 = min(numeric_x), max(numeric_x)
            else:
                x0, x1 = 0, max(len(xs) - 1, 1)
            y0, y1 = min(flat), max(flat)
            if x1 == x0:
                x1 = x0 + 1
            if y1 == y0:
                y1 = y0 + 1

            def px(x, i):
                v = x if isinstance(x, (int, float)) else i
                return P + (v - x0) / (x1 - x0) * (W - 2 * P)

            def py(y):
                return H - P - (y - y0) / (y1 - y0) * (H - 2 * P)

            for si, (name, vals) in enumerate(sorted(ys.items())):
                color = self._COLORS[si % len(self._COLORS)]
                pts = [
                    f"{px(x, i):.1f},{py(v):.1f}"
                    for i, (x, v) in enumerate(zip(xs, vals))
                    if isinstance(v, (int, float))
                ]
                if len(pts) > 1:
                    parts.append(
                        f'<polyline points="{" ".join(pts)}" fill="none" '
                        f'stroke="{color}" stroke-width="1.5"/>'
                    )
                for p in pts:
                    cx, cy = p.split(",")
                    parts.append(
                        f'<circle cx="{cx}" cy="{cy}" r="2.5" fill="{color}"/>'
                    )
                parts.append(
                    f'<text x="{W - P + 5}" y="{P + 14 * (si + 1)}" '
                    f'fill="{color}" font-size="12">{_html.escape(name)}</text>'
                )
            for frac, val in ((0.0, y0), (1.0, y1)):
                parts.append(
                    f'<text x="{P - 5}" y="{H - P - frac * (H - 2 * P) + 4}" '
                    f'text-anchor="end" font-size="11">{val:g}</text>'
                )
            for frac, val in ((0.0, x0), (1.0, x1)):
                label = f"{val:g}" if isinstance(val, (int, float)) else str(val)
                parts.append(
                    f'<text x="{P + frac * (W - 2 * P)}" y="{H - P + 16}" '
                    f'text-anchor="middle" font-size="11">{_html.escape(label)}</text>'
                )
        parts.append("</svg>")
        return "".join(parts)

    def _repr_html_(self) -> str:
        return self.to_svg()

    def save_html(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(
                "<!DOCTYPE html><html><body>" + self.to_svg() + "</body></html>"
            )


def plot(
    table: Table,
    plotting_function: Callable | None = None,
    sorting_col: str | None = None,
) -> Any:
    """Live plot of a table (reference ``stdlib/viz`` Bokeh integration).

    With Bokeh installed and a ``plotting_function(source) -> figure``,
    drives a live ``ColumnDataSource`` exactly like the reference;
    otherwise returns a :class:`LivePlot` SVG chart fed by the same
    subscription."""
    try:
        import bokeh.models  # noqa: F401

        have_bokeh = True
    except ImportError:
        have_bokeh = False
    if have_bokeh:
        # outside the probe try: an ImportError raised by the user's
        # plotting_function must propagate, not trigger the SVG fallback
        return _bokeh_plot(table, plotting_function, sorting_col)
    view = LivePlot(table._column_names, sorting_col)

    def on_change(key, row, time, is_addition):
        if is_addition:
            view.rows[key] = tuple(row.values())
        else:
            view.rows.pop(key, None)

    pw.io.subscribe(table, on_change=on_change, name="viz_plot")
    return view


def _bokeh_plot(
    table: Table, plotting_function: Callable | None, sorting_col: str | None
) -> Any:
    from bokeh.models import ColumnDataSource

    source = ColumnDataSource(data={c: [] for c in table._column_names})
    state: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[key] = tuple(row.values())
        else:
            state.pop(key, None)

    def on_time_end(time):
        cols = table._column_names
        rows = list(state.values())
        if sorting_col in cols:
            si = cols.index(sorting_col)
            rows.sort(key=lambda r: (r[si] is None, r[si]))
        source.data = {c: [r[i] for r in rows] for i, c in enumerate(cols)}

    pw.io.subscribe(
        table, on_change=on_change, on_time_end=on_time_end, name="viz_plot"
    )
    if plotting_function is not None:
        return plotting_function(source)
    return source


show = table_viz
