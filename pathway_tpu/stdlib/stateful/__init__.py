"""Stateful operators (reference ``python/pathway/stdlib/stateful/``)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table

__all__ = ["deduplicate"]


def deduplicate(
    table: Table,
    *,
    value: Any,
    instance: Any = None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
) -> Table:
    """Keep one accepted row per instance (reference
    ``stdlib/stateful/deduplicate.py:9`` → engine ``deduplicate``
    ``src/engine/graph.rs:895``)."""
    return table.deduplicate(value=value, instance=instance, acceptor=acceptor)
