"""Asof joins (reference ``stdlib/temporal/_asof_join.py:479+`` and
``_asof_now_join.py:176+``)."""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.engine.temporal import AsofJoinNode
from pathway_tpu.internals.joins import JoinKind, JoinResult
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.temporal._interval_join import _compile_side, _split_on

__all__ = [
    "Direction",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
]


class Direction(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


def _asof(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
    direction: Direction = Direction.BACKWARD,
    as_of_now: bool = False,
    defaults: dict | None = None,
) -> JoinResult:
    lt = _compile_side(self, self_time)
    rt = _compile_side(other, other_time)
    ljk, rjk = _split_on(on, self, other)
    kind = "inner" if how == JoinKind.INNER else "left"
    node = AsofJoinNode(
        G.engine_graph,
        self._node,
        other._node,
        ljk,
        rjk,
        lt,
        rt,
        left_ncols=len(self._column_names),
        right_ncols=len(other._column_names),
        direction=direction.value if isinstance(direction, Direction) else direction,
        kind=kind,
        as_of_now=as_of_now,
    )
    # analyzer annotation: asof keeps one match per left row under a
    # watermark discipline — time-bounded state (PW-S001 near-miss)
    node.meta["temporal"] = {
        "kind": "asof_join",
        "direction": direction.value if isinstance(direction, Direction) else direction,
        "bounded": True,
        "as_of_now": as_of_now,
    }
    return JoinResult(self, other, [], how, _node=node)


def asof_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
    direction: Direction = Direction.BACKWARD,
    defaults: dict | None = None,
    behavior: Any = None,
) -> JoinResult:
    """reference ``asof_join`` — each left row matched with the closest
    right row by time within the same key group."""
    return _asof(
        self, other, self_time, other_time, *on,
        how=how, direction=direction, defaults=defaults,
    )


def asof_join_left(self, other, self_time, other_time, *on, **kw):
    kw.setdefault("how", JoinKind.LEFT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_join_right(self, other, self_time, other_time, *on, **kw):
    # right asof = left asof with sides swapped
    kw.setdefault("how", JoinKind.LEFT)
    return asof_join(other, self, other_time, self_time, *on, **kw)


def asof_join_outer(self, other, self_time, other_time, *on, **kw):
    kw.setdefault("how", JoinKind.LEFT)
    return asof_join(self, other, self_time, other_time, *on, **kw)


def asof_now_join(
    self: Table,
    other: Table,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
    **kw: Any,
) -> JoinResult:
    """reference ``asof_now_join`` — left rows are matched ONCE against
    the right side's state at their arrival epoch (no revision when the
    right side later changes)."""
    from pathway_tpu.engine.temporal import AsofNowJoinNode

    ljk, rjk = _split_on(on, self, other)
    node = AsofNowJoinNode(
        G.engine_graph,
        self._node,
        other._node,
        ljk,
        rjk,
        left_ncols=len(self._column_names),
        right_ncols=len(other._column_names),
        kind="left" if how == JoinKind.LEFT else "inner",
    )
    # analyzer annotation: matches once at arrival epoch, no revision —
    # the left side is never buffered (PW-S001 near-miss)
    node.meta["temporal"] = {"kind": "asof_now_join", "bounded": True}
    return JoinResult(self, other, [], how, _node=node)


def asof_now_join_inner(self, other, *on, **kw):
    return asof_now_join(self, other, *on, how=JoinKind.INNER, **kw)


def asof_now_join_left(self, other, *on, **kw):
    return asof_now_join(self, other, *on, how=JoinKind.LEFT, **kw)
