"""Interval joins (reference ``stdlib/temporal/_interval_join.py:577+``)."""

from __future__ import annotations

import dataclasses
from typing import Any

from pathway_tpu.engine.temporal import IntervalJoinNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import BinaryExpression, ColumnExpression, _wrap
from pathway_tpu.internals.joins import JoinResult
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left as LEFT, right as RIGHT, this as THIS

__all__ = [
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
]


@dataclasses.dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound: Any, upper_bound: Any) -> Interval:
    return Interval(lower_bound, upper_bound)


def _compile_side(table: Table, expr: Any):
    e = _wrap(expr)._substitute({THIS: table, LEFT: table, RIGHT: table})
    layout = table._layout()
    c = e._compile(layout.resolver)
    return lambda k, v: c((k, v))


def _split_on(on: tuple, left: Table, right: Table):
    lfns, rfns = [], []
    for cond in on:
        cond = _wrap(cond)._substitute({LEFT: left, RIGHT: right})
        if not (isinstance(cond, BinaryExpression) and cond._op == "=="):
            raise ValueError("interval_join conditions must be equalities")
        a, b = cond._left, cond._right
        a_tabs = {r._table for r in a._references()}
        if left in a_tabs or any(getattr(t, "_layout_token", None) is left._layout_token for t in a_tabs):
            la, ra = a, b
        else:
            la, ra = b, a
        llayout = left._layout()
        rlayout = right._layout()
        lc = la._compile(llayout.resolver)
        rc = ra._compile(rlayout.resolver)
        lfns.append(lc)
        rfns.append(rc)
    return (
        lambda k, v: tuple(f((k, v)) for f in lfns),
        lambda k, v: tuple(f((k, v)) for f in rfns),
    )


def interval_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    interval: Interval,
    *on: Any,
    how: str = "inner",
    behavior: Any = None,
) -> JoinResult:
    """reference ``interval_join`` — returns a JoinResult for .select()."""
    from pathway_tpu.internals.joins import JoinKind

    lt = _compile_side(self, self_time)
    rt = _compile_side(other, other_time)
    ljk, rjk = _split_on(on, self, other)
    node = IntervalJoinNode(
        G.engine_graph,
        self._node,
        other._node,
        ljk,
        rjk,
        lt,
        rt,
        interval.lower_bound,
        interval.upper_bound,
        left_ncols=len(self._column_names),
        right_ncols=len(other._column_names),
        kind=how,
    )
    # analyzer annotation (graph_facts): finite interval bounds make this
    # a time-windowed construct — state is watermark-evicted, so it does
    # not accumulate unboundedly the way a plain join over a live source
    # does (PW-S001 near-miss)
    node.meta["temporal"] = {
        "kind": "interval_join",
        "how": how,
        "bounded": True,
        "lower": interval.lower_bound,
        "upper": interval.upper_bound,
    }
    return JoinResult(self, other, [], JoinKind[how.upper()], _node=node)


def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(self, other, self_time, other_time, interval, *on, how="inner", **kw)


def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(self, other, self_time, other_time, interval, *on, how="left", **kw)


def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(self, other, self_time, other_time, interval, *on, how="right", **kw)


def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(self, other, self_time, other_time, interval, *on, how="outer", **kw)