"""Window joins (reference ``stdlib/temporal/_window_join.py``): rows
join when their windows coincide (plus optional equality conditions).
Use ``pw.left`` / ``pw.right`` in the conditions."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.joins import JoinKind, JoinResult
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.temporal._window import Window, windowby

__all__ = [
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
    "WindowJoinResult",
]


class WindowJoinResult(JoinResult):
    """JoinResult whose sides carry ``_pw_window`` columns."""


def _assigned(table: Table, time_expr: Any, window: Window) -> Table:
    return windowby(table, time_expr, window=window)._assigned


def window_join(
    self: Table,
    other: Table,
    self_time: Any,
    other_time: Any,
    window: Window,
    *on: Any,
    how: JoinKind = JoinKind.INNER,
) -> JoinResult:
    left_a = _assigned(self, self_time, window)
    right_a = _assigned(other, other_time, window)
    import pathway_tpu as pw

    conds = [pw.left["_pw_window"] == pw.right["_pw_window"], *on]
    return WindowJoinResult(left_a, right_a, conds, how)


def window_join_inner(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.INNER)


def window_join_left(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.LEFT)


def window_join_right(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.RIGHT)


def window_join_outer(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinKind.OUTER)
