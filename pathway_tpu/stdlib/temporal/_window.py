"""Windows + ``windowby`` (reference ``stdlib/temporal/_window.py:595-905``).

Window assignment is a stateless rowwise flatten (a row can land in
several sliding windows); grouped reduction rides the engine's
incremental GroupByNode; behaviors (delay/cutoff/keep_results) are the
engine :class:`TemporalBehaviorNode` between assignment and reduction,
driven by the event-time watermark.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import pathway_tpu as pw
from pathway_tpu.engine import graph as eg
from pathway_tpu.engine.temporal import TemporalBehaviorNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys as K
from pathway_tpu.internals.expression import ColumnExpression, _wrap
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this as THIS
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
)

__all__ = [
    "Window",
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "windowby",
    "WindowedTable",
]


class Window:
    def assign(self, t: Any, instance: Any) -> list[tuple]:
        """-> list of (instance, start, end) window triples."""
        raise NotImplementedError


@dataclasses.dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None
    offset: Any = None

    def assign(self, t, instance):
        origin = self.origin if self.origin is not None else (self.offset or 0)
        n = math.floor((t - origin) / self.duration)
        start = origin + n * self.duration
        return [(instance, start, start + self.duration)]


@dataclasses.dataclass
class SlidingWindow(Window):
    hop: Any
    duration: Any
    origin: Any = None
    offset: Any = None

    def assign(self, t, instance):
        origin = self.origin if self.origin is not None else (self.offset or 0)
        out = []
        # windows [s, s+duration) with s = origin + i*hop containing t
        first = math.floor((t - self.duration - origin) / self.hop) + 1
        i = first
        while True:
            s = origin + i * self.hop
            if s > t:
                break
            if t < s + self.duration:
                out.append((instance, s, s + self.duration))
            i += 1
        return out


@dataclasses.dataclass
class SessionWindow(Window):
    """Session windows merge rows closer than ``max_gap`` (or linked by
    ``predicate``); assignment is stateful per instance, handled by
    :class:`SessionAssignNode`."""

    predicate: Any = None
    max_gap: Any = None


@dataclasses.dataclass
class IntervalsOverWindow(Window):
    at: Any  # ColumnReference with the probe time points
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True  # match the intervals_over() factory default


def tumbling(duration: Any, origin: Any = None, offset: Any = None) -> TumblingWindow:
    return TumblingWindow(duration, origin, offset)


def sliding(hop: Any, duration: Any = None, ratio: int | None = None, origin: Any = None, offset: Any = None) -> SlidingWindow:
    if duration is None:
        assert ratio is not None, "sliding() needs duration or ratio"
        duration = hop * ratio
    return SlidingWindow(hop, duration, origin, offset)


def session(predicate: Any = None, max_gap: Any = None) -> SessionWindow:
    if (predicate is None) == (max_gap is None):
        raise ValueError("session() needs exactly one of predicate / max_gap")
    return SessionWindow(predicate, max_gap)


def intervals_over(*, at: Any, lower_bound: Any, upper_bound: Any, is_outer: bool = True) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


class SessionAssignNode(eg.Node):
    """Stateful session clustering: per instance, sort rows by time and
    merge neighbours per max_gap/predicate; dirty instances re-cluster
    (reference session windows in ``_window.py:595+``)."""

    def __init__(self, graph, input, time_fn, instance_fn, window: SessionWindow, name="session_assign"):
        super().__init__(graph, [input], name)
        self.time_fn = time_fn
        self.instance_fn = instance_fn
        self.window = window

    def make_state(self):
        # instances: inst -> {row_key: (values, time)}; out: row_key -> assigned values
        return {"instances": {}, "out": {}}

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.stream import consolidate, hashable

        st = ctx.state(self)
        dirty = set()
        for u in consolidate(inbatches[0]):
            inst = hashable(self.instance_fn(u.key, u.values))
            rows = st["instances"].setdefault(inst, {})
            if u.diff > 0:
                rows[u.key] = (u.values, self.time_fn(u.key, u.values))
            else:
                rows.pop(u.key, None)
            dirty.add(inst)
        out = []
        for inst in dirty:
            rows = st["instances"].get(inst, {})
            ordering = sorted(rows.items(), key=lambda kv: (kv[1][1], str(kv[0])))
            # cluster
            clusters: list[list] = []
            prev_t = None
            for rk, (values, t) in ordering:
                new = prev_t is None
                if not new:
                    if self.window.max_gap is not None:
                        new = (t - prev_t) > self.window.max_gap
                    else:
                        new = not self.window.predicate(prev_t, t)
                if new:
                    clusters.append([])
                clusters[-1].append((rk, values, t))
                prev_t = t
            assigned: dict = {}
            for cluster in clusters:
                start = min(t for _, _, t in cluster)
                end = max(t for _, _, t in cluster)
                for rk, values, _t in cluster:
                    assigned[rk] = values + ((inst, start, end),)
            for rk, row in assigned.items():
                old = st["out"].get(rk)
                if old != row:
                    if old is not None:
                        out.append(eg.Update(rk, old, -1))
                    out.append(eg.Update(rk, row, 1))
                    st["out"][rk] = row
        # rows removed from the input retract their assignment
        for u in inbatches[0]:
            if u.diff < 0 and u.key in st["out"]:
                old = st["out"].pop(u.key)
                out.append(eg.Update(u.key, old, -1))
        return consolidate(out)


class WindowedTable:
    """Result of ``windowby``: call ``.reduce(...)``.  Inside reduce,
    ``pw.this._pw_window_start`` / ``_pw_window_end`` / ``_pw_instance``
    /``_pw_window`` are available (reference window columns)."""

    def __init__(self, assigned: Table, shard_expr: Any):
        self._assigned = assigned
        self._shard = shard_expr

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        t = self._assigned
        grouped = t.groupby(t["_pw_window"])
        extras = self._gather(args, kwargs)
        extras.pop("_pw_window", None)
        out = grouped.reduce(_pw_window=t["_pw_window"], **extras)
        final = out.with_columns(
            _pw_instance=pw.apply(lambda w: w[0], out["_pw_window"]),
            _pw_window_start=pw.apply(lambda w: w[1], out["_pw_window"]),
            _pw_window_end=pw.apply(lambda w: w[2], out["_pw_window"]),
        )
        return final

    def _gather(self, args, kwargs) -> dict[str, Any]:
        from pathway_tpu.internals.expression import smart_name

        t = self._assigned
        window_cols = {
            "_pw_window": t["_pw_window"],
            "_pw_window_start": pw.apply(lambda w: w[1], t["_pw_window"]),
            "_pw_window_end": pw.apply(lambda w: w[2], t["_pw_window"]),
            "_pw_instance": pw.apply(lambda w: w[0], t["_pw_window"]),
        }
        out: dict[str, Any] = {}
        for a in args:
            e = _wrap(a)._substitute({THIS: t})
            n = smart_name(e)
            if n is None:
                raise ValueError("positional reduce() args must be named columns")
            out[n] = window_cols.get(n, e)
        for n, a in kwargs.items():
            e = _wrap(a)._substitute({THIS: t})
            if isinstance(a, str) and a in window_cols:
                e = window_cols[a]
            out[n] = window_cols.get(getattr(e, "_name", None), e)
        return out


def windowby(
    table: Table,
    time_expr: Any,
    *,
    window: Window,
    behavior: Behavior | None = None,
    instance: Any = None,
    shard: Any = None,
) -> WindowedTable:
    """reference ``_window.py:windowby`` (``:820+``)"""
    time_e = _wrap(time_expr)._substitute({THIS: table})
    inst_e = (
        _wrap(instance if instance is not None else shard)._substitute({THIS: table})
        if (instance is not None or shard is not None)
        else None
    )
    layout = table._layout()
    tc = time_e._compile(layout.resolver)
    ic = inst_e._compile(layout.resolver) if inst_e is not None else (lambda kv: None)

    if isinstance(window, IntervalsOverWindow):
        return _intervals_over_windowby(table, tc, ic, window, behavior)

    if isinstance(window, SessionWindow):
        node = SessionAssignNode(
            G.engine_graph,
            table._node,
            lambda k, v: tc((k, v)),
            lambda k, v: ic((k, v)),
            window,
        )
        # analyzer annotation (graph_facts): session assignment is a
        # windowing construct — bounds downstream stateful key spaces
        node.meta["temporal"] = {"kind": "session_window", "bounded": True}
        assigned = Table(
            node,
            table._column_names + ["_pw_window"],
            {**table._dtypes, "_pw_window": dt.ANY},
            name="session_windows",
        )
    else:
        win = window

        def assign_row(key, values):
            t = tc((key, values))
            inst = ic((key, values))
            return values + (tuple(win.assign(t, inst)),)

        rnode = eg.RowwiseNode(G.engine_graph, table._node, assign_row, name="window_assign")
        rnode.meta["temporal"] = {
            "kind": "window_assign",
            "window": type(win).__name__,
            "bounded": True,
        }
        multi = Table(
            rnode,
            table._column_names + ["_pw_windows"],
            {**table._dtypes, "_pw_windows": dt.ANY},
            name="window_assign",
        )
        flat = multi.flatten(multi["_pw_windows"])
        assigned = flat.select(
            *[flat[c] for c in table._column_names],
            _pw_window=flat["_pw_windows"],
        )

    if behavior is not None:
        # original column positions are preserved in `assigned`, so the
        # compiled time accessor works on its rows: the watermark advances
        # by TRUE event time
        assigned = _apply_behavior(assigned, behavior, lambda k, v: tc((k, v)))
    return WindowedTable(assigned, inst_e)


def _apply_behavior(
    assigned: Table, behavior: Behavior, time_fn, window_end_offset: Any = 0
) -> Table:
    """``window_end_offset`` shifts where a window CLOSES relative to its
    tuple's end field: intervals_over windows store the probe point p in
    both slots while their data band extends to p + upper_bound — the
    cutoff/shift must anchor at the band end, or in-band rows past the
    probe freeze their own window (late-row loss)."""
    widx = assigned._column_names.index("_pw_window")
    off = window_end_offset

    if isinstance(behavior, ExactlyOnceBehavior):
        shift = (behavior.shift or 0) + off
        # exactly-once: buffer the whole window, release at close + shift,
        # then freeze (late rows dropped); results kept
        thr_fn = lambda k, v, s=shift: v[widx][2] + s  # noqa: E731
        exp_fn = lambda k, v, s=shift: v[widx][2] + s  # noqa: E731
        node = TemporalBehaviorNode(
            G.engine_graph,
            assigned._node,
            time_fn=time_fn,
            threshold_fn=thr_fn,
            expiry_fn=exp_fn,
            keep_results=True,
        )
        node.meta["temporal"] = {
            "kind": "behavior",
            "behavior": "exactly_once",
            "bounded": True,
        }
        return Table(
            node, assigned._column_names, assigned._dtypes, name="exactly_once"
        )

    assert isinstance(behavior, CommonBehavior)
    delay = behavior.delay
    cutoff = behavior.cutoff
    thr_fn = (
        (lambda k, v, d=delay: v[widx][1] + d) if delay is not None else None
    )
    exp_fn = (
        (lambda k, v, c=cutoff + off: v[widx][2] + c)
        if cutoff is not None
        else None
    )
    node = TemporalBehaviorNode(
        G.engine_graph,
        assigned._node,
        time_fn=time_fn,
        threshold_fn=thr_fn,
        expiry_fn=exp_fn,
        keep_results=behavior.keep_results,
    )
    node.meta["temporal"] = {
        "kind": "behavior",
        "behavior": "common",
        "bounded": True,
        "keep_results": behavior.keep_results,
    }
    return Table(node, assigned._column_names, assigned._dtypes, name="behavior")


def _intervals_over_windowby(table, tc, ic, window: IntervalsOverWindow, behavior):
    """intervals_over: a window per probe point p = [p+lower, p+upper]."""
    at_ref = window.at
    at_table: Table = at_ref._table
    at_layout = at_table._layout()
    ac = _wrap(at_ref)._compile(at_layout.resolver)

    class ProbeAssignNode(eg.Node):
        """Pair data rows with probe points within the band; stateful on
        both sides (a small dedicated interval join)."""

        def __init__(self, graph, data, probes, name="intervals_over"):
            super().__init__(graph, [data, probes], name)

        def make_state(self):
            return {"data": {}, "probes": {}, "out": {}}

        def process(self, ctx, time, inbatches):
            from pathway_tpu.engine.stream import consolidate

            st = ctx.state(self)
            for u in consolidate(inbatches[0]):
                if u.diff > 0:
                    st["data"][u.key] = (u.values, tc((u.key, u.values)), ic((u.key, u.values)))
                else:
                    st["data"].pop(u.key, None)
            for u in consolidate(inbatches[1]):
                if u.diff > 0:
                    st["probes"][u.key] = (ac((u.key, u.values)), None)
                else:
                    st["probes"].pop(u.key, None)
            # recompute full assignment (dirty-all; probe sets are small)
            new_out: dict = {}
            matched: set = set()
            for dk, (values, t, inst) in st["data"].items():
                for pk, (p, _) in st["probes"].items():
                    if p + window.lower_bound <= t <= p + window.upper_bound:
                        okey = K.derive(dk, "iv", int(pk))
                        new_out[okey] = values + ((inst, p, p),)
                        matched.add(pk)
            if window.is_outer:
                # outer: a probe with no data in its band still yields a
                # window — one placeholder row of Nones (reference
                # intervals_over is_outer)
                n_data_cols = len(table._column_names)
                for pk, (p, _) in st["probes"].items():
                    if pk not in matched:
                        okey = K.derive(pk, "iv_outer")
                        new_out[okey] = (None,) * n_data_cols + ((None, p, p),)
            out = []
            for okey, row in new_out.items():
                if st["out"].get(okey) != row:
                    if okey in st["out"]:
                        out.append(eg.Update(okey, st["out"][okey], -1))
                    out.append(eg.Update(okey, row, 1))
            for okey in list(st["out"]):
                if okey not in new_out:
                    out.append(eg.Update(okey, st["out"][okey], -1))
            st["out"] = new_out
            return consolidate(out)

    node = ProbeAssignNode(G.engine_graph, table._node, at_table._node)
    node.meta["temporal"] = {"kind": "intervals_over", "bounded": True}
    assigned = Table(
        node,
        table._column_names + ["_pw_window"],
        {**table._dtypes, "_pw_window": dt.ANY},
        name="intervals_over",
    )
    if behavior is not None:
        # behaviors act on the data rows' TRUE event time, like the
        # fixed-window paths; the window tuple stores the probe point p
        # in both slots, so closing anchors at the BAND end
        # p + upper_bound via the offset (placeholder outer rows carry
        # time None and pass through untouched by the watermark)
        assigned = _apply_behavior(
            assigned,
            behavior,
            lambda k, v: tc((k, v)),
            window_end_offset=window.upper_bound,
        )
    return WindowedTable(assigned, None)
