"""Temporal behaviors (reference
``stdlib/temporal/temporal_behavior.py:21-100``)."""

from __future__ import annotations

import dataclasses
from typing import Any

__all__ = ["Behavior", "CommonBehavior", "ExactlyOnceBehavior", "common_behavior", "exactly_once_behavior"]


class Behavior:
    pass


@dataclasses.dataclass
class CommonBehavior(Behavior):
    """delay: buffer rows until watermark >= window_start + delay;
    cutoff: freeze/forget at window_end + cutoff;
    keep_results: whether closed windows' results stay in the output."""

    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(delay: Any = None, cutoff: Any = None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


@dataclasses.dataclass
class ExactlyOnceBehavior(Behavior):
    """Each window produces exactly one output, shift after it closes
    (reference ``exactly_once_behavior``)."""

    shift: Any = None


def exactly_once_behavior(shift: Any = None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)
