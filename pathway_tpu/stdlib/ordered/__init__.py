"""Ordered operations: sorting index and ``diff``.

Reference: ``python/pathway/stdlib/ordered/diff.py`` (prev/next via sorting
index, ``src/engine/dataflow/operators/prev_next.rs``).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnReference, _wrap
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this as THIS

__all__ = ["sort", "diff"]


def sort(table: Table, key: Any = None, instance: Any = None) -> Table:
    """Return a table (same universe) with ``prev``/``next`` Optional[Pointer]
    columns ordering rows by ``key`` within ``instance``."""
    key_expr = _wrap(key if key is not None else ColumnReference(table, "id"))
    key_expr = key_expr._substitute({THIS: table})
    layout = table._layout()
    kc = key_expr._compile(layout.resolver)
    if instance is not None:
        ic = _wrap(instance)._substitute({THIS: table})._compile(layout.resolver)
    else:
        ic = lambda kv: ()
    node = eg.SortNode(
        G.engine_graph,
        table._node,
        lambda k, v: kc((k, v)),
        lambda k, v: ic((k, v)),
    )
    return Table(
        node,
        ["prev", "next"],
        {"prev": dt.Optional(dt.POINTER), "next": dt.Optional(dt.POINTER)},
        name=f"{table._name}.sort",
        layout_token=table._layout_token,
    )


def diff(table: Table, timestamp: Any, *values: Any, instance: Any = None) -> Table:
    """Per-row difference vs the previous row when ordered by ``timestamp``
    (reference ``stdlib/ordered/diff.py``: ``diff_<col>`` columns; None for
    the first row)."""
    import pathway_tpu as pw

    sorted_ix = sort(table, key=timestamp, instance=instance)
    combined = table.with_columns(pw_prev_=sorted_ix.prev)
    prev_rows = table.ix(combined["pw_prev_"], optional=True, context=combined)
    out_cols = {}
    for v in values:
        e = _wrap(v)._substitute({THIS: table})
        if not isinstance(e, ColumnReference):
            raise TypeError("diff() values must be column references")
        name = e._name
        out_cols[f"diff_{name}"] = pw.require(
            table[name] - prev_rows[name], prev_rows[name]
        )
    return combined.with_columns(**out_cols).without("pw_prev_")
