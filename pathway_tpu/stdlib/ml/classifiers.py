"""kNN classifiers (reference ``stdlib/ml/classifiers/`` — LSH-bucketed
kNN with majority vote, ``_knn_lsh.py:64-306``).

Two candidate-search engines:

- the exact TPU index (default — brute-force matmul outruns host LSH at
  the target scales), and
- a REAL LSH banding structure (:class:`LshBandingIndex` +
  :func:`generate_euclidean_lsh_bucketer` /
  :func:`generate_cosine_lsh_bucketer`), faithful to the reference's
  scheme: L bands of M hashes; a query's candidates are the union of its
  matching band buckets, re-ranked by the exact distance.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Any, Callable

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = [
    "knn_lsh_classifier_train",
    "knn_lsh_train",
    "knn_lsh_classify",
    "generate_euclidean_lsh_bucketer",
    "generate_cosine_lsh_bucketer",
    "LshBandingIndex",
]


def generate_euclidean_lsh_bucketer(
    d: int, M: int, L: int, A: float, seed: int = 0
) -> Callable[[np.ndarray], list]:
    """p-stable Euclidean LSH (reference
    ``_lsh.generate_euclidean_lsh_bucketer``): each of the L bands hashes
    a vector to a tuple of M quantized projections
    ``floor((x . v + b) / A)``."""
    rng = np.random.default_rng(seed)
    proj = rng.normal(size=(L * M, d))  # [L*M, d]
    offs = rng.uniform(0, A, size=(L * M,))

    def bucketer(x: Any) -> list:
        x = np.asarray(x, np.float64).reshape(-1)
        h = np.floor((proj @ x + offs) / A).astype(np.int64)
        return [tuple(h[i * M : (i + 1) * M]) for i in range(L)]

    return bucketer


def generate_cosine_lsh_bucketer(
    d: int, M: int, L: int, seed: int = 0
) -> Callable[[np.ndarray], list]:
    """Signed-random-hyperplane LSH (reference
    ``generate_cosine_lsh_bucketer``): each band is M sign bits."""
    rng = np.random.default_rng(seed)
    planes = rng.normal(size=(L * M, d))

    def bucketer(x: Any) -> list:
        x = np.asarray(x, np.float64).reshape(-1)
        bits = (planes @ x >= 0).astype(np.int64)
        out = []
        for i in range(L):
            band = bits[i * M : (i + 1) * M]
            out.append(int("".join(map(str, band)), 2))
        return out

    return bucketer


class LshBandingIndex:
    """Banded LSH candidate index with exact re-ranking (the reference's
    ``knn_lsh_generic_classifier_train`` data structure, host-side)."""

    def __init__(
        self,
        d: int,
        *,
        L: int = 20,
        M: int = 10,
        A: float = 10.0,
        metric: str = "euclidean",
        seed: int = 0,
    ):
        if metric == "euclidean":
            self.bucketer = generate_euclidean_lsh_bucketer(d, M, L, A, seed)
            self._dist = lambda q, x: float(np.sum((q - x) ** 2))
        elif metric == "cosine":
            self.bucketer = generate_cosine_lsh_bucketer(d, M, L, seed)

            def _cos(q, x):
                nq = np.linalg.norm(q) or 1.0
                nx = np.linalg.norm(x) or 1.0
                return 1.0 - float(q @ x) / (nq * nx)

            self._dist = _cos
        else:
            raise ValueError(f"unsupported LSH metric {metric!r}")
        self.L = L
        #: band index: buckets[band_i][band_hash] -> set of keys
        self.buckets: list[dict[Any, set]] = [defaultdict(set) for _ in range(L)]
        self.vectors: dict[Any, np.ndarray] = {}

    def __len__(self) -> int:
        return len(self.vectors)

    def add(self, key: Any, vector: Any) -> None:
        if key in self.vectors:
            self.remove(key)
        v = np.asarray(vector, np.float64).reshape(-1)
        self.vectors[key] = v
        for band_i, h in enumerate(self.bucketer(v)):
            self.buckets[band_i][h].add(key)

    def remove(self, key: Any) -> None:
        v = self.vectors.pop(key, None)
        if v is None:
            return
        for band_i, h in enumerate(self.bucketer(v)):
            self.buckets[band_i][h].discard(key)

    def candidates(self, query: Any) -> set:
        """Union of the query's matching band buckets."""
        q = np.asarray(query, np.float64).reshape(-1)
        out: set = set()
        for band_i, h in enumerate(self.bucketer(q)):
            out |= self.buckets[band_i].get(h, set())
        return out

    def query(self, query: Any, k: int) -> list[tuple[Any, float]]:
        """Top-k (key, distance) among LSH candidates — approximate: a
        point sharing no band bucket with the query is never considered."""
        q = np.asarray(query, np.float64).reshape(-1)
        scored = [
            (key, self._dist(q, self.vectors[key])) for key in self.candidates(q)
        ]
        scored.sort(key=lambda kv: (kv[1], str(kv[0])))
        return scored[:k]


def knn_lsh_train(
    data: Table,
    L: int = 20,
    d: int | None = None,
    M: int = 10,
    A: float = 10.0,
    type: str = "euclidean",  # noqa: A002 — reference parameter name
    embedding_column: str = "data",
    label_column: str = "label",
) -> KNNIndex:
    """Build the classifier index (reference ``knn_lsh_classifier_train``)."""
    assert d is not None, "pass d (embedding dimensions)"
    return KNNIndex(
        data[embedding_column], data, n_dimensions=d, n_or=L, n_and=M,
        bucket_length=A, distance_type=type,
    )


knn_lsh_classifier_train = knn_lsh_train


def knn_lsh_classify(
    index: KNNIndex, data_queries: Any, queries: Table | None = None, k: int = 3
) -> Table:
    """Classify queries by majority vote over the k nearest neighbours
    (reference ``knn_lsh_classify``)."""
    replies = index.get_nearest_items(data_queries, k=k, collapse_rows=True)

    def vote(labels) -> Any:
        from collections import Counter

        labels = [l for l in (labels or ()) if l is not None]
        if not labels:
            return None
        return Counter(labels).most_common(1)[0][0]

    return replies.select(predicted_label=pw.apply(vote, replies.label))
