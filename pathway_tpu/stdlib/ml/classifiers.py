"""kNN classifiers (reference ``stdlib/ml/classifiers/`` — LSH-bucketed
kNN with majority vote, ``_knn_lsh.py:64-306``).  Here the candidate
search is the exact TPU index; voting logic matches the reference."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = ["knn_lsh_classifier_train", "knn_lsh_train", "knn_lsh_classify"]


def knn_lsh_train(
    data: Table,
    L: int = 20,
    d: int | None = None,
    M: int = 10,
    A: float = 10.0,
    type: str = "euclidean",  # noqa: A002 — reference parameter name
    embedding_column: str = "data",
    label_column: str = "label",
) -> KNNIndex:
    """Build the classifier index (reference ``knn_lsh_classifier_train``)."""
    assert d is not None, "pass d (embedding dimensions)"
    return KNNIndex(
        data[embedding_column], data, n_dimensions=d, n_or=L, n_and=M,
        bucket_length=A, distance_type=type,
    )


knn_lsh_classifier_train = knn_lsh_train


def knn_lsh_classify(
    index: KNNIndex, data_queries: Any, queries: Table | None = None, k: int = 3
) -> Table:
    """Classify queries by majority vote over the k nearest neighbours
    (reference ``knn_lsh_classify``)."""
    replies = index.get_nearest_items(data_queries, k=k, collapse_rows=True)

    def vote(labels) -> Any:
        from collections import Counter

        labels = [l for l in (labels or ()) if l is not None]
        if not labels:
            return None
        return Counter(labels).most_common(1)[0][0]

    return replies.select(predicted_label=pw.apply(vote, replies.label))
