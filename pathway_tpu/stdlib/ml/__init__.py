"""``pw.ml`` (reference ``python/pathway/stdlib/ml/``): legacy KNNIndex,
classifiers (incl. real LSH banding), HMM, smart-table fuzzy join."""

from pathway_tpu.stdlib.ml import classifiers, hmm, smart_table_ops
from pathway_tpu.stdlib.ml.classifiers import (
    LshBandingIndex,
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
)
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = [
    "KNNIndex",
    "LshBandingIndex",
    "classifiers",
    "generate_cosine_lsh_bucketer",
    "generate_euclidean_lsh_bucketer",
    "hmm",
    "smart_table_ops",
]
