"""``pw.ml`` (reference ``python/pathway/stdlib/ml/``): legacy KNNIndex,
classifiers, HMM, smart-table fuzzy join."""

from pathway_tpu.stdlib.ml.index import KNNIndex
from pathway_tpu.stdlib.ml import classifiers, hmm, smart_table_ops

__all__ = ["KNNIndex", "classifiers", "hmm", "smart_table_ops"]
