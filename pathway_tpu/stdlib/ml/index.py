"""Legacy ``KNNIndex`` API (reference ``stdlib/ml/index.py:9-300``) over
the TPU-sharded brute-force index (the reference used a pure-Python LSH
implementation, ``ml/classifiers/_knn_lsh.py``)."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import BruteForceKnnFactory, DataIndex

__all__ = ["KNNIndex"]


class KNNIndex:
    """reference ``KNNIndex(data_embedding, data, n_dimensions, ...)``"""

    def __init__(
        self,
        data_embedding: Any,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: Any = None,
    ):
        metric = "l2sq" if distance_type == "euclidean" else "cos"
        factory = BruteForceKnnFactory(
            dimensions=n_dimensions,
            reserved_space=max(1024, n_or * 64),
            metric=metric,
        )
        self._index: DataIndex = factory.build_data_index(
            data_embedding, data, metadata_column=metadata
        )
        self._data = data

    def get_nearest_items(
        self,
        query_embedding: Any,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: Any = None,
    ) -> Table:
        """Fully consistent queries (reference ``get_nearest_items``)."""
        return self._pack(
            self._index.query(
                query_embedding, number_of_matches=k, metadata_filter=metadata_filter
            ),
            collapse_rows,
            with_distances,
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: Any,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: Any = None,
    ) -> Table:
        return self._pack(
            self._index.query_as_of_now(
                query_embedding, number_of_matches=k, metadata_filter=metadata_filter
            ),
            collapse_rows,
            with_distances,
        )

    def _pack(self, replies: Table, collapse_rows: bool, with_distances: bool) -> Table:
        data_cols = self._data._column_names

        def collapse(datas, scores):
            cols = {
                c: tuple((d or {}).get(c) for d in (datas or ()))
                for c in data_cols
            }
            if with_distances:
                cols["dist"] = tuple(-float(s) for s in (scores or ()))
            return cols

        packed = replies.select(
            *[
                replies[c]
                for c in replies._column_names
                if not c.startswith("_pw_index_reply")
            ],
            _pw_packed=pw.apply(
                collapse, replies["_pw_index_reply"], replies["_pw_index_reply_score"]
            ),
        )
        out_cols = data_cols + (["dist"] if with_distances else [])
        result = packed.select(
            *[packed[c] for c in packed._column_names if c != "_pw_packed"],
            **{
                c: pw.apply(lambda p, c=c: p[c], packed["_pw_packed"])
                for c in out_cols
            },
        )
        return result
