"""Fuzzy join (reference ``stdlib/ml/smart_table_ops/_fuzzy_join.py``, 470
LoC): match rows of two tables by text similarity."""

from __future__ import annotations

import re
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = ["fuzzy_match_tables", "fuzzy_self_match", "smart_fuzzy_match"]

_TOKEN = re.compile(r"[a-z0-9]+")


def _tokens(s: str) -> set[str]:
    return set(_TOKEN.findall(str(s).lower()))


def _score(a: str, b: str) -> float:
    ta, tb = _tokens(a), _tokens(b)
    if not ta or not tb:
        return 0.0
    return len(ta & tb) / len(ta | tb)


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    left_column: Any = None,
    right_column: Any = None,
    threshold: float = 0.2,
) -> Table:
    """Best-match pairs (left, right, weight) by Jaccard token similarity,
    greedy highest-weight-first (the reference's matching discipline)."""
    lcol = left_column if left_column is not None else left_table[left_table._column_names[0]]
    rcol = right_column if right_column is not None else right_table[right_table._column_names[0]]

    lpacked = left_table.reduce(
        rows=pw.reducers.tuple(
            pw.apply(lambda k, v: (k, v), left_table.id, lcol)
        )
    )
    rpacked = right_table.reduce(
        rows=pw.reducers.tuple(
            pw.apply(lambda k, v: (k, v), right_table.id, rcol)
        )
    )

    def match(lrows, rrows):
        pairs = []
        for lk, lv in lrows or ():
            for rk, rv in rrows or ():
                s = _score(lv, rv)
                if s >= threshold:
                    pairs.append((s, lk, rk))
        pairs.sort(key=lambda p: (-p[0], str(p[1]), str(p[2])))
        used_l: set = set()
        used_r: set = set()
        out = []
        for s, lk, rk in pairs:
            if lk in used_l or rk in used_r:
                continue
            used_l.add(lk)
            used_r.add(rk)
            out.append((lk, rk, s))
        return tuple(out)

    matches = lpacked.join(rpacked).select(
        pairs=pw.apply(match, pw.left.rows, pw.right.rows)
    )
    flat = matches.flatten(matches.pairs)
    return flat.select(
        left=pw.apply(lambda p: p[0], flat.pairs),
        right=pw.apply(lambda p: p[1], flat.pairs),
        weight=pw.apply(lambda p: p[2], flat.pairs),
    )


def fuzzy_self_match(table: Table, column: Any = None, **kwargs: Any) -> Table:
    return fuzzy_match_tables(table, table, left_column=column, right_column=column, **kwargs)


smart_fuzzy_match = fuzzy_match_tables
