"""Fuzzy join (reference ``stdlib/ml/smart_table_ops/_fuzzy_join.py``):
match rows of two tables by weighted feature similarity — tokenize or
letter features, inverse-frequency normalization (discrete weight /
logweight), greedy highest-weight matching, with optional by-hand
overrides (``smart_fuzzy_match``)."""

from __future__ import annotations

import math
import re
from enum import IntEnum, auto
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals.table import Table

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match_tables",
    "fuzzy_self_match",
    "smart_fuzzy_match",
]

_TOKEN = re.compile(r"[a-z0-9]+")


def _tokenize(s: Any) -> set[str]:
    return set(_TOKEN.findall(str(s).lower()))


def _letters(s: Any) -> set[str]:
    return {ch for ch in str(s).lower() if ch.isalnum()}


class FuzzyJoinFeatureGeneration(IntEnum):
    """reference ``FuzzyJoinFeatureGeneration`` (AUTO == TOKENIZE)."""

    AUTO = auto()
    TOKENIZE = auto()
    LETTERS = auto()

    @property
    def generate(self) -> Callable[[Any], set]:
        if self == FuzzyJoinFeatureGeneration.LETTERS:
            return _letters
        return _tokenize


def _discrete_weight(cnt: float) -> float:
    return 0.0 if cnt == 0 else 1 / (2 ** math.ceil(math.log2(cnt)))


def _discrete_logweight(cnt: float) -> float:
    return 0.0 if cnt == 0 else 1 / math.ceil(math.log2(cnt + 1))


class FuzzyJoinNormalization(IntEnum):
    """reference ``FuzzyJoinNormalization``: a feature appearing in cnt
    rows contributes weight(cnt) to a match (rare features dominate)."""

    WEIGHT = auto()
    LOGWEIGHT = auto()
    NONE = auto()

    @property
    def normalize(self) -> Callable[[float], float]:
        if self == FuzzyJoinNormalization.WEIGHT:
            return _discrete_weight
        if self == FuzzyJoinNormalization.LOGWEIGHT:
            return _discrete_logweight
        return lambda cnt: 1.0


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    left_column: Any = None,
    right_column: Any = None,
    threshold: float = 0.0,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.AUTO,
    by_hand_match: "Table | None" = None,
) -> Table:
    """Best-match pairs (left, right, weight): features from both sides,
    inverse-frequency weighting, greedy highest-weight-first matching
    (the reference's discipline).  ``by_hand_match`` rows (left, right,
    weight) are fixed first and excluded from fuzzy matching."""
    lcol = left_column if left_column is not None else left_table[left_table._column_names[0]]
    rcol = right_column if right_column is not None else right_table[right_table._column_names[0]]

    lpacked = left_table.reduce(
        rows=pw.reducers.tuple(
            pw.apply(lambda k, v: (k, v), left_table.id, lcol)
        )
    )
    rpacked = right_table.reduce(
        rows=pw.reducers.tuple(
            pw.apply(lambda k, v: (k, v), right_table.id, rcol)
        )
    )
    gen = feature_generation.generate
    norm = normalization.normalize

    def match(lrows, rrows, fixed):
        lrows = lrows or ()
        rrows = rrows or ()
        lfeat = {lk: gen(lv) for lk, lv in lrows}
        rfeat = {rk: gen(rv) for rk, rv in rrows}
        # global feature frequency over BOTH sides -> per-feature weight
        cnt: dict = {}
        for feats in list(lfeat.values()) + list(rfeat.values()):
            for f in feats:
                cnt[f] = cnt.get(f, 0) + 1
        w = {f: norm(c) for f, c in cnt.items()}
        used_l = {lk for lk, _rk, _w in fixed}
        used_r = {rk for _lk, rk, _w in fixed}
        # inverted index: only compare pairs sharing at least one feature
        by_feature: dict = {}
        for rk, feats in rfeat.items():
            for f in feats:
                by_feature.setdefault(f, []).append(rk)
        pairs = []
        for lk, feats in lfeat.items():
            cands: set = set()
            for f in feats:
                cands.update(by_feature.get(f, ()))
            for rk in cands:
                score = sum(w[f] for f in feats & rfeat[rk])
                if score > threshold:
                    pairs.append((score, lk, rk))
        pairs.sort(key=lambda p: (-p[0], str(p[1]), str(p[2])))
        out = list(fixed)
        for score, lk, rk in pairs:
            if lk in used_l or rk in used_r:
                continue
            used_l.add(lk)
            used_r.add(rk)
            out.append((lk, rk, score))
        return tuple(out)

    if by_hand_match is not None:
        hand = by_hand_match.reduce(
            fixed=pw.reducers.tuple(
                pw.apply(
                    lambda l, r, w: (l, r, float(w)),
                    by_hand_match.left,
                    by_hand_match.right,
                    by_hand_match.weight,
                )
            )
        )
        matches = (
            lpacked.join(rpacked)
            .select(rows=pw.left.rows, rrows=pw.right.rows)
            .join_left(hand)  # empty overrides table must NOT drop matches
            .select(
                pairs=pw.apply(
                    lambda lr, rr, f: match(lr, rr, list(f or ())),
                    pw.left.rows,
                    pw.left.rrows,
                    pw.right.fixed,
                )
            )
        )
    else:
        matches = lpacked.join(rpacked).select(
            pairs=pw.apply(lambda lr, rr: match(lr, rr, []), pw.left.rows, pw.right.rows)
        )
    flat = matches.flatten(matches.pairs)
    return flat.select(
        left=pw.apply(lambda p: p[0], flat.pairs),
        right=pw.apply(lambda p: p[1], flat.pairs),
        weight=pw.apply(lambda p: p[2], flat.pairs),
    )


def fuzzy_self_match(table: Table, column: Any = None, **kwargs: Any) -> Table:
    return fuzzy_match_tables(table, table, left_column=column, right_column=column, **kwargs)


smart_fuzzy_match = fuzzy_match_tables
