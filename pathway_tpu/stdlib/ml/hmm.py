"""Hidden Markov model smoothing (reference ``stdlib/ml/hmm.py``, 210 LoC:
``create_hmm_reducer`` — Viterbi decoding over recent observations,
packaged as a stateful reducer)."""

from __future__ import annotations

from typing import Any, Callable, Hashable

import numpy as np

from pathway_tpu.reducers import _StatefulReducer

__all__ = ["create_hmm_reducer"]


def create_hmm_reducer(
    graph: dict[Hashable, dict[Hashable, float]] | None = None,
    *,
    states: list | None = None,
    transition: Any = None,
    emission: Callable[[Any, Any], float] | None = None,
    num_results_kept: int | None = 100,
) -> Any:
    """Stateful reducer decoding the most likely CURRENT hidden state from
    the group's observations (Viterbi forward pass).

    Apply to ``(time, observation)`` tuples so decoding respects event
    order::

        smoothed = t.groupby(t.k).reduce(
            state=hmm_reducer(pw.make_tuple(t.t, t.obs)))

    Either pass ``graph`` = {state: {state: prob}} plus optional
    ``emission(state, obs) -> prob``, or ``states`` + ``transition``.
    """
    if graph is not None:
        states = list(graph.keys())
        trans = np.array(
            [[graph[a].get(b, 1e-12) for b in states] for a in states], np.float64
        )
    else:
        assert states is not None and transition is not None
        trans = np.asarray(transition, np.float64)
    log_trans = np.log(np.maximum(trans, 1e-300))
    n = len(states)
    emit_fn = emission or (lambda state, obs: 1.0 if state == obs else 1e-6)
    keep = num_results_kept or 100

    def fold(rows: list[Any]) -> Any:
        # rows: multiset of (time, obs) argument tuples; sort by time
        seq = sorted((r[0] if len(r) == 1 else r for r in rows), key=lambda p: p[0])
        seq = seq[-keep:]
        scores = np.zeros(n, np.float64)
        for _t, obs in seq:
            emit = np.log(
                np.maximum([emit_fn(s, obs) for s in states], 1e-300)
            )
            scores = np.max(scores[:, None] + log_trans, axis=0) + emit
            scores -= scores.max()
        return states[int(np.argmax(scores))] if len(seq) else None

    return _StatefulReducer(fold, name="hmm")
