"""``pw.io.pubsub`` — Google Pub/Sub sink (reference
``python/pathway/io/pubsub``).

The reference API takes the CONFIGURED ``pubsub_v1.PublisherClient`` as
an argument — the publisher is the injection point by design, so tests
pass a double with ``topic_path``/``publish``.  The table must have a
single binary/string payload column; the connector adds ``pathway_time``
and ``pathway_diff`` attributes to every message.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer

__all__ = ["write"]


class _PubSubWriter(Writer):
    def __init__(self, publisher: Any, project_id: str, topic_id: str, column: str):
        self.publisher = publisher
        self.topic = publisher.topic_path(project_id, topic_id)
        self.column = column
        self._futures: list[Any] = []

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        payload = row[self.column]
        if isinstance(payload, str):
            payload = payload.encode()
        elif not isinstance(payload, (bytes, bytearray)):
            payload = str(payload).encode()
        fut = self.publisher.publish(
            self.topic,
            data=bytes(payload),
            pathway_time=str(time),
            pathway_diff=str(diff),
        )
        if fut is not None:
            self._futures.append(fut)

    def flush(self) -> None:
        for fut in self._futures:
            result = getattr(fut, "result", None)
            if result is not None:
                result()
        self._futures = []


def write(table: Table, publisher: Any, project_id: str, topic_id: str) -> None:
    """Publish the table's change stream to a Pub/Sub topic; ``table``
    must have exactly one (binary/string) payload column."""
    cols = table.column_names()
    if len(cols) != 1:
        raise ValueError(
            f"pw.io.pubsub.write expects a single payload column; got {cols}"
        )
    attach_writer(
        table,
        _PubSubWriter(publisher, project_id, topic_id, cols[0]),
        name="pubsub_out",
    )
