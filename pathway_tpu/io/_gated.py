"""Helper for service-backed connectors whose client libraries are not in
this environment: expose the reference API shape, fail with a clear message
at call time (not import time)."""

from __future__ import annotations

from typing import Any, Callable


class MissingDependency(ImportError):
    pass


def require(*candidates: str) -> Any:
    """Import the first available client module or raise MissingDependency."""
    import importlib

    errors = []
    for name in candidates:
        try:
            return importlib.import_module(name)
        except ImportError as e:
            errors.append(str(e))
    raise MissingDependency(
        f"none of the client libraries {candidates} are installed in this "
        "environment; this connector keeps the reference API surface and "
        "activates when a client is available"
    )


def gated_reader(connector: str, *deps: str) -> Callable:
    def read(*args: Any, **kwargs: Any) -> Any:
        require(*deps)
        raise NotImplementedError(
            f"pw.io.{connector}.read: client available but integration not wired"
        )

    return read


def gated_writer(connector: str, *deps: str) -> Callable:
    def write(*args: Any, **kwargs: Any) -> Any:
        require(*deps)
        raise NotImplementedError(
            f"pw.io.{connector}.write: client available but integration not wired"
        )

    return write
