"""``pw.io.nats`` — NATS connector (reference ``python/pathway/io/nats``;
Rust reader ``src/connectors/data_storage.rs:2271``, writer ``:2345``).

Messages are subject-addressed payloads.  The client is injectable — a
minimal duck-typed broker with ``publish(subject, payload, headers)``
and ``subscribe(subject, on_message) -> unsubscribe`` (tests use the
in-process :class:`MockNats`); without one, the async ``nats-py`` client
is wrapped in a background asyncio loop.

Formats follow the reference: reader ``raw``/``plaintext`` (autogen key,
single ``data`` column) or ``json``; writer ``json``/``plaintext`` with
``pathway_time``/``pathway_diff`` headers on every message.
"""

from __future__ import annotations

import json as _json
import threading
import time as _time
from collections import defaultdict
from typing import Any, Callable

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, Writer, attach_writer, coerce_row, fmt_value, input_table
from pathway_tpu.io._gated import MissingDependency

__all__ = ["read", "write", "MockNats"]


class MockNats:
    """In-process NATS double (the kafka MockBroker pattern): pub/sub by
    subject, shared per uri via ``MockNats.get("mock://name")``."""

    _instances: dict[str, "MockNats"] = {}
    _lock = threading.Lock()

    def __init__(self):
        self._subs: dict[str, list[Callable]] = defaultdict(list)
        self.published: list[tuple[str, bytes, dict]] = []

    @classmethod
    def get(cls, uri: str) -> "MockNats":
        with cls._lock:
            return cls._instances.setdefault(uri, cls())

    def publish(self, subject: str, payload: bytes, headers: dict | None = None) -> None:
        self.published.append((subject, payload, headers or {}))
        for cb in list(self._subs.get(subject, ())):
            cb(payload, headers or {})

    def subscribe(self, subject: str, on_message: Callable) -> Callable:
        self._subs[subject].append(on_message)

        def unsubscribe():
            try:
                self._subs[subject].remove(on_message)
            except ValueError:
                pass

        return unsubscribe


def _client_for(uri: str, client: Any) -> Any:
    if client is not None:
        return client
    if uri.startswith("mock://"):
        return MockNats.get(uri)
    try:
        import nats  # type: ignore[import-not-found]  # noqa: F401
    except ImportError as e:
        raise MissingDependency(
            "nats-py is not installed; pass client= with a "
            "publish/subscribe-capable object or use a mock:// uri"
        ) from e
    return _AsyncNatsBridge(uri)


class _AsyncNatsBridge:
    """Wraps the asyncio nats-py client behind the sync duck-type."""

    def __init__(self, uri: str):
        import asyncio

        import nats  # type: ignore[import-not-found]

        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever, daemon=True).start()
        fut = asyncio.run_coroutine_threadsafe(nats.connect(uri), self._loop)
        self._nc = fut.result(timeout=30)

    def publish(self, subject, payload, headers=None):
        import asyncio

        asyncio.run_coroutine_threadsafe(
            self._nc.publish(subject, payload, headers=headers or {}), self._loop
        ).result(timeout=30)

    def subscribe(self, subject, on_message):
        import asyncio

        async def handler(msg):
            on_message(msg.data, dict(msg.headers or {}))

        fut = asyncio.run_coroutine_threadsafe(
            self._nc.subscribe(subject, cb=handler), self._loop
        )
        sub = fut.result(timeout=30)

        def unsubscribe():
            asyncio.run_coroutine_threadsafe(
                sub.unsubscribe(), self._loop
            ).result(timeout=30)

        return unsubscribe


class _NatsSource(RowSource):
    deterministic_replay = False  # live subject; no replay from broker

    def __init__(self, uri: str, topic: str, schema, format: str, client: Any):
        self.uri = uri
        self.topic = topic
        self.schema = schema
        self.format = format
        self.client = client
        self._seq = 0

    def run(self, events: Any) -> None:
        client = _client_for(self.uri, self.client)
        lock = threading.Lock()

        def on_message(payload: bytes, headers: dict) -> None:
            with lock:
                self._seq += 1
                seq = self._seq
            if self.format == "raw":
                values = {"data": payload}
            elif self.format == "plaintext":
                values = {"data": payload.decode(errors="replace")}
            else:  # json
                try:
                    values = _json.loads(payload)
                except Exception:
                    return
                if not isinstance(values, dict):
                    return
            pk = self.schema.primary_key_columns()
            if pk:
                key = ref_scalar(*[values.get(c) for c in pk])
            else:
                key = ref_scalar("__nats__", self.topic, seq)
            events.add(key, coerce_row(values, self.schema))
            events.commit()

        unsubscribe = client.subscribe(self.topic, on_message)
        try:
            while not events.stopped:
                _time.sleep(0.1)
        finally:
            unsubscribe()


class _NatsWriter(Writer):
    def __init__(self, uri: str, topic: str, format: str, value_col: str | None, client: Any):
        self.uri = uri
        self.topic = topic
        self.format = format
        self.value_col = value_col
        self._client = client

    def _get_client(self):
        if self._client is None or isinstance(self._client, str):
            self._client = _client_for(self.uri, None)
        return self._client

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        if self.format == "plaintext":
            col = self.value_col or next(k for k in row if k != "id")
            payload = str(row[col]).encode()
        else:  # json
            doc = {k: fmt_value(v) for k, v in row.items() if k != "id"}
            payload = _json.dumps(doc).encode()
        self._get_client().publish(
            self.topic,
            payload,
            {"pathway_time": str(time), "pathway_diff": str(diff)},
        )


def read(
    uri: str,
    topic: str,
    *,
    schema: sch.SchemaMetaclass | None = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    client: Any = None,
    name: str = "nats",
    **kwargs: Any,
) -> Table:
    """Subscribe to a NATS subject; ``raw``/``plaintext`` yield a single
    ``data`` column, ``json`` parses the payload against ``schema``."""
    if schema is None:
        schema = sch.schema_from_types(data=bytes if format == "raw" else str)
    src = _NatsSource(uri, topic, schema, format, client)
    return input_table(src, schema, name=name)


def write(
    table: Table,
    uri: str,
    topic: str,
    *,
    format: str = "json",
    value: Any = None,
    headers: Any = None,
    client: Any = None,
    name: str = "nats_out",
    **kwargs: Any,
) -> None:
    """Publish the table's change stream to a NATS subject."""
    value_col = getattr(value, "_name", value) if value is not None else None
    attach_writer(
        table, _NatsWriter(uri, topic, format, value_col, client), name=name
    )
