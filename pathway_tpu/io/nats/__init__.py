"""``pw.io.nats`` — NATS connector (reference python/pathway/io/nats; reader src/connectors/data_storage.rs:2271, writer :2345).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

read = gated_reader("nats", "nats")
write = gated_writer("nats", "nats")

__all__ = ["read", "write"]
