"""Connector framework: reader subjects feeding the engine, writer sinks.

Capability parity with the reference connector layer
(``src/connectors/mod.rs`` ``Connector::run``, ``data_storage.rs`` readers,
``data_format.rs`` parsers/formatters): a reader thread parses events into
keyed rows and commits epochs; a writer subscribes to a table's update
stream and formats rows out.  The engine side is
:class:`pathway_tpu.engine.graph.InputNode` (+ scheduler event queue).
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from typing import Any, Callable, Iterable

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import native as _nat
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

class _AutogenCounter:
    """Process-global sequence for auto-generated row keys.  Unlike
    ``itertools.count`` it can be observed and fast-forwarded, which
    persistence uses to guarantee resumed runs never re-issue a sequence
    number that a replayed key already embeds."""

    def __init__(self) -> None:
        self._n = 0
        self._lock = threading.Lock()

    def __next__(self) -> int:
        with self._lock:
            v = self._n
            self._n += 1
            return v

    def peek(self) -> int:
        return self._n

    def advance_to(self, n: int) -> None:
        with self._lock:
            self._n = max(self._n, n)


_autogen_counter = _AutogenCounter()


class RowSource:
    """Engine-facing subject: ``run(events)`` called on a reader thread with
    an event sink (add/remove/commit/close)."""

    #: True for readers that re-emit their full history deterministically
    #: (enables count-based persistence resume; see pathway_tpu.persistence)
    deterministic_replay = False

    #: how rows split across workers in a multi-worker run: "single"
    #: (one reader owns the whole stream), "byte-range" (static files
    #: split by offset), "round-robin", or "key" (routed by row key).
    #: Consumed by the distribution-safety pass (analysis/distribution.py).
    partitioning = "single"

    #: whether per-key arrival order survives a partitioned multi-worker
    #: read.  Byte-range file splits do NOT preserve it (PR 9 gotcha).
    order_preserving = True

    def run(self, events: Any) -> None:  # pragma: no cover
        raise NotImplementedError


def key_for_row(
    values: dict[str, Any],
    pk_columns: list[str] | None,
    seq: int | None = None,
    source_tag: str = "",
) -> K.Pointer:
    """Row key: hash of primary-key values when declared, else sequential
    (reference keys from pk columns or connector offsets)."""
    if pk_columns:
        return K.ref_scalar(*[values[c] for c in pk_columns])
    return K.ref_scalar("__autogen__", source_tag, seq if seq is not None else next(_autogen_counter))


_coercer_cache: dict[Any, list] = {}


def _column_coercer(dtype: Any):
    """Per-dtype coercion closure — same semantics as ``dt.coerce`` with the
    dtype dispatch hoisted out of the per-row loop."""
    base = dtype.strip_optional()
    if base == dt.FLOAT:

        def co(v):
            if isinstance(v, float):
                return v
            if isinstance(v, int):
                return float(v)
            if isinstance(v, str):
                try:
                    return float(v)
                except ValueError:
                    return v
            return v

    elif base == dt.INT:

        def co(v):
            if isinstance(v, int):
                return v
            if isinstance(v, float) and v.is_integer():
                return int(v)
            if isinstance(v, str):
                try:
                    return int(v)
                except ValueError:
                    return v
            return v

    elif base == dt.STR:

        def co(v):
            return v if isinstance(v, str) else str(v)

    elif base == dt.BOOL:

        def co(v):
            if isinstance(v, str):
                return v.lower() in ("true", "1", "t", "yes")
            return v

    else:

        def co(v):
            return v

    return co


#: native coercion codes (native/pathway_native.cpp CoerceCode); every
#: dtype outside this map coerces as identity (code 0)
_NATIVE_CODES = {dt.INT: 1, dt.FLOAT: 2, dt.STR: 3, dt.BOOL: 4}


def _schema_plans(schema: sch.SchemaMetaclass) -> tuple[list, tuple]:
    """One cached plan per schema, built once: the Python coercer closures
    and the equivalent native code table share the same (name, default)
    extraction so the two paths cannot drift apart."""
    plans = _coercer_cache.get(schema)
    if plans is None:
        cols = [
            (name, col.default_value if col.has_default else None, col.dtype)
            for name, col in schema.__columns__.items()
        ]
        py_plan = [(n, d, _column_coercer(t)) for n, d, t in cols]
        native_plan = tuple(
            (n, d, _NATIVE_CODES.get(t.strip_optional(), 0)) for n, d, t in cols
        )
        plans = (py_plan, native_plan)
        _coercer_cache[schema] = plans
    return plans


def _schema_coercers(schema: sch.SchemaMetaclass) -> list:
    return _schema_plans(schema)[0]


def coerce_row(values: dict[str, Any], schema: sch.SchemaMetaclass) -> tuple:
    out = []
    for name, default, co in _schema_coercers(schema):
        v = values.get(name)
        if v is None:
            v = default
        out.append(co(v) if v is not None else None)
    return tuple(out)


def coerce_rows(rows: list, schema: sch.SchemaMetaclass) -> list:
    """Bulk :func:`coerce_row` over a block of parsed row dicts — one C
    call when the native extension is available (reference parser hot
    loop, ``src/connectors/data_format.rs``)."""
    native = _nat.load()
    if native is not None:
        try:
            return native.coerce_rows(rows, _schema_plans(schema)[1])
        except native.Unsupported:
            pass
    return [coerce_row(v, schema) for v in rows]


def input_table(
    subject: RowSource | None,
    schema: sch.SchemaMetaclass,
    *,
    static_rows: Iterable[tuple[K.Pointer, tuple]] = (),
    name: str = "connector",
    upsert: bool = False,
    auxiliary: bool = False,
    persistent_id: str | None = None,
    recovery_policy: Any = None,
    on_overflow: str | None = None,
) -> Table:
    cols = schema.column_names()
    if on_overflow is not None:
        from pathway_tpu.engine.scheduler import INGEST_OVERFLOW_MODES

        if on_overflow not in INGEST_OVERFLOW_MODES:
            raise ValueError(
                f"on_overflow must be one of {INGEST_OVERFLOW_MODES}, "
                f"got {on_overflow!r}"
            )
    node = eg.InputNode(
        G.engine_graph,
        n_cols=len(cols),
        static_rows=static_rows,
        subject=subject,
        name=name,
        upsert=upsert,
    )
    # auxiliary inputs (e.g. AsyncTransformer loopbacks) don't keep the
    # run alive on their own; the scheduler exits when primaries close
    # and auxiliaries report no pending work
    node.auxiliary = auxiliary
    # explicit snapshot identity (reference persistent_id): names the
    # snapshot stream stably across graph edits, and opts the source into
    # SELECTIVE_PERSISTING
    node.persistent_id = persistent_id
    # restart/backoff/breaker supervision (ConnectorRecoveryPolicy,
    # pathway_tpu.internals.resilience); None keeps the historical
    # one-failure-drops-the-source behaviour
    node.recovery_policy = recovery_policy
    # ingest-buffer overflow policy ("pause" | "shed_oldest" | "fail");
    # None defaults to "pause" — the reader parks until the drain frees
    # credit (see engine.scheduler.IngestCredit)
    node.on_overflow = on_overflow
    # distribution-safety facts for the analyzer: static tables live on
    # every worker identically; live sources advertise how they split and
    # whether per-key order survives the split (analysis/distribution.py)
    dtypes = {c: schema.__columns__[c].dtype for c in cols}
    node.meta["source"] = {
        "name": name,
        "upsert": upsert,
        "partitioning": (
            "static" if subject is None else getattr(subject, "partitioning", "single")
        ),
        "order_preserving": (
            True if subject is None else bool(getattr(subject, "order_preserving", True))
        ),
        "dtypes": list(dtypes.values()),
    }
    return Table(node, cols, dtypes, name=name)


class DictSource(RowSource):
    """Reader emitting parsed dict rows via a user-supplied generator; commits
    an epoch per ``commit_every`` rows or ``commit_interval`` seconds."""

    deterministic_replay = True

    def __init__(
        self,
        row_iter: Callable[[], Iterable[dict[str, Any] | tuple[str, dict[str, Any]]]],
        schema: sch.SchemaMetaclass,
        *,
        commit_every: int | None = None,
        commit_interval: float | None = None,
        tag: str = "",
    ):
        self.row_iter = row_iter
        self.schema = schema
        self.commit_every = commit_every
        self.commit_interval = commit_interval
        self.tag = tag

    def run(self, events: Any) -> None:
        pk = self.schema.primary_key_columns()
        n = 0
        last_commit = _time.monotonic()
        for item in self.row_iter():
            if events.stopped:
                break
            if isinstance(item, tuple) and len(item) == 2 and item[0] in ("add", "remove"):
                op, values = item
            else:
                op, values = "add", item
            key = key_for_row(values, pk, seq=None, source_tag=self.tag)
            row = coerce_row(values, self.schema)
            if op == "add":
                events.add(key, row)
            else:
                events.remove(key, row)
            n += 1
            now = _time.monotonic()
            if (self.commit_every and n % self.commit_every == 0) or (
                self.commit_interval and now - last_commit >= self.commit_interval
            ):
                events.commit()
                last_commit = now
        events.commit()


# ---------------------------------------------------------------------------
# Writers


class Writer:
    """Formats and persists one row update (reference ``trait Writer``,
    ``src/connectors/data_storage.rs:619``)."""

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class LazyFileWriter(Writer):
    """File-backed writer that opens lazily on first row.

    In a process cluster every process builds the graph, but only worker 0
    receives output rows — an eager ``open(path, "w")`` in ``__init__``
    would let a peer process truncate worker 0's file.  ``close()`` (called
    only on the owning worker) still creates/truncates the file even when
    the run emitted zero rows, so stale output from a previous run never
    survives a successful empty run."""

    _open_newline: str | None = None

    def __init__(self, path: str):
        self._path = path
        self._f: Any = None
        self._resumed = False

    def _file(self):
        if self._f is None:
            # after a checkpoint resume the committed prefix up to the
            # watermark must survive — append instead of truncating
            mode = "a" if self._resumed else "w"
            self._f = open(self._path, mode, newline=self._open_newline)
        return self._f

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        self._file().close()

    def watermark(self) -> int:
        """Byte offset of everything emitted so far (the sink-dedup
        watermark checkpointed with the operator state).  Flushes first so
        the offset covers the epoch just closed; measured with getsize —
        byte-exact, unlike text-mode ``tell()`` cookies."""
        if self._f is not None:
            self._f.flush()
            return os.path.getsize(self._path)
        if self._resumed and os.path.exists(self._path):
            return os.path.getsize(self._path)
        return 0

    def resume_at(self, offset: int) -> bool:
        """Roll the output file back to a checkpointed watermark: truncate
        to ``offset`` bytes and flip subsequent opens to append, so the
        recovered file is exactly the checkpointed prefix plus the
        replayed tail (duplicate emissions from replayed epochs are
        suppressed by construction).  False when the file is gone or
        shorter than the watermark — the sink then rewrites from scratch,
        which is still correct (full replay reproduces every row)."""
        if self._f is not None:
            return False  # already emitting: too late to roll back
        try:
            if os.path.getsize(self._path) < offset:
                return False
            with open(self._path, "r+b") as f:
                f.truncate(offset)
            self._resumed = True
            return True
        except OSError:
            return False


def attach_writer(table: Table, writer: Writer, *, name: str = "output") -> None:
    cols = table._column_names

    def on_change(key: K.Pointer, values: tuple, time: int, diff: int) -> None:
        row = dict(zip(cols, values))
        row["id"] = key
        writer.write(row, time, diff)

    def on_time_end(time: int) -> None:
        writer.flush()

    def on_end() -> None:
        writer.flush()
        writer.close()

    node = eg.OutputNode(
        G.engine_graph,
        table._node,
        on_change,
        on_time_end,
        on_end,
        name=name,
        writer=writer,  # enables checkpointed sink-dedup watermarks
    )
    node.meta["sink"] = {
        "names": list(cols),
        "dtypes": dict(table._dtypes),
    }


def format_change_row(row: dict[str, Any], time: int, diff: int) -> dict[str, Any]:
    """Standard change-stream document for service sinks: formatted row
    columns (``id`` dropped) plus integral ``time``/``diff`` fields — the
    reference's writer contract (a modification = a -1 doc then a +1 doc)."""
    doc = {k: fmt_value(v) for k, v in row.items() if k != "id"}
    doc["time"] = time
    doc["diff"] = diff
    return doc


def fmt_key(v: Any) -> str:
    """Canonical sink serialization of a row key: the full 128-bit value,
    NOT repr (repr truncates to 12 chars — two distinct keys could print
    identically).  One format across every sink, so ids correlate.
    Non-Pointer ids pass through as plain strings."""
    if isinstance(v, K.Pointer):
        return f"^{int(v):032X}"
    return str(v)


def fmt_value(v: Any) -> Any:
    import datetime

    import numpy as np

    from pathway_tpu.internals.api import ERROR
    from pathway_tpu.internals.json import Json

    if isinstance(v, K.Pointer):
        return fmt_key(v)
    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (datetime.datetime, datetime.timedelta)):
        return str(v)
    if v is ERROR:
        return "Error"
    if isinstance(v, tuple):
        return [fmt_value(x) for x in v]
    return v
