"""``pw.io.kafka`` — Kafka connector (reference ``python/pathway/io/kafka``;
engine reader ``src/connectors/data_storage.rs:692``, writer ``:1258``).

Two transports:

- a real broker via the ``kafka-python`` client when installed;
- an in-process :class:`MockBroker` (``bootstrap.servers: "mock://..."``),
  used by tests and benchmarks in environments without services — same
  partitioned, offset-ordered semantics on the framework side.
"""

from __future__ import annotations

import json as _json
import threading
import time as _time
from collections import defaultdict
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import (
    RowSource,
    Writer,
    attach_writer,
    coerce_row,
    fmt_value,
    input_table,
    key_for_row,
)

__all__ = ["read", "write", "simple_read", "MockBroker"]


class MockBroker:
    """In-process topic store with Kafka-ish semantics (append-only
    partitioned logs, consumer offsets)."""

    _instances: dict[str, "MockBroker"] = {}
    _lock = threading.Lock()

    def __init__(self) -> None:
        self.topics: dict[str, list[tuple[bytes | None, bytes]]] = defaultdict(list)
        self.closed_topics: set[str] = set()
        self.cond = threading.Condition()

    @classmethod
    def get(cls, url: str) -> "MockBroker":
        with cls._lock:
            if url not in cls._instances:
                cls._instances[url] = cls()
            return cls._instances[url]

    def produce(self, topic: str, value: bytes, key: bytes | None = None) -> None:
        with self.cond:
            self.topics[topic].append((key, value))
            self.cond.notify_all()

    def close_topic(self, topic: str) -> None:
        with self.cond:
            self.closed_topics.add(topic)
            self.cond.notify_all()

    def consume_from(self, topic: str, offset: int, timeout: float = 0.5) -> list[tuple[bytes | None, bytes]]:
        with self.cond:
            if len(self.topics[topic]) <= offset and topic not in self.closed_topics:
                self.cond.wait(timeout)
            return self.topics[topic][offset:]

    def is_closed(self, topic: str) -> bool:
        with self.cond:
            return topic in self.closed_topics


def _parse_message(
    raw: bytes,
    format: str,
    schema: sch.SchemaMetaclass | None,
    dsv_separator: str = ";",
) -> dict[str, Any] | None:
    if format == "raw":
        return {"data": raw.decode(errors="replace")}
    if format == "json":
        try:
            obj = _json.loads(raw)
        except _json.JSONDecodeError:
            return None
        return obj if isinstance(obj, dict) else None
    if format == "dsv":
        # separator-delimited values in schema column order (reference DSV
        # parser, src/connectors/data_format.rs:500)
        if schema is None:
            return None
        parts = raw.decode(errors="replace").rstrip("\n").split(dsv_separator)
        cols = schema.column_names()
        if len(parts) < len(cols):
            return None
        return dict(zip(cols, parts))
    raise ValueError(f"unsupported kafka format {format!r}")


class _MockKafkaSource(RowSource):
    def __init__(
        self,
        broker: MockBroker,
        topic: str,
        schema: sch.SchemaMetaclass,
        format: str,
        mode: str,
        commit_every: int = 256,
    ):
        self.broker = broker
        self.topic = topic
        self.schema = schema
        self.format = format
        self.mode = mode
        self.commit_every = commit_every

    def run(self, events: Any) -> None:
        pk = self.schema.primary_key_columns()
        offset = 0
        seq = 0
        while not events.stopped:
            msgs = self.broker.consume_from(self.topic, offset)
            for _key, raw in msgs:
                values = _parse_message(raw, self.format, self.schema)
                offset += 1
                if values is None:
                    continue
                seq += 1
                key = key_for_row(values, pk, seq=seq, source_tag=f"kafka:{self.topic}")
                events.add(key, coerce_row(values, self.schema))
                if seq % self.commit_every == 0:
                    events.commit()
            events.commit()
            if self.broker.is_closed(self.topic) and offset >= len(self.broker.topics[self.topic]):
                return
            if self.mode == "static" and not msgs:
                return


class _KafkaClientSource(RowSource):
    def __init__(self, settings: dict, topic: str, schema: sch.SchemaMetaclass, format: str):
        self.settings = settings
        self.topic = topic
        self.schema = schema
        self.format = format

    def run(self, events: Any) -> None:
        from kafka import KafkaConsumer  # type: ignore[import-not-found]

        consumer = KafkaConsumer(
            self.topic,
            bootstrap_servers=self.settings.get("bootstrap.servers"),
            group_id=self.settings.get("group.id"),
            auto_offset_reset=self.settings.get("auto.offset.reset", "earliest"),
        )
        pk = self.schema.primary_key_columns()
        seq = 0
        try:
            # poll with a timeout (instead of blocking iteration) so scheduler
            # shutdown is observed between batches
            while not events.stopped:
                batches = consumer.poll(timeout_ms=500)
                for msgs in batches.values():
                    for msg in msgs:
                        values = _parse_message(msg.value, self.format, self.schema)
                        if values is None:
                            continue
                        seq += 1
                        key = key_for_row(
                            values, pk, seq=seq, source_tag=f"kafka:{self.topic}"
                        )
                        events.add(key, coerce_row(values, self.schema))
                if batches:
                    events.commit()
        finally:
            consumer.close()


def read(
    rdkafka_settings: dict,
    topic: str | None = None,
    *,
    schema: sch.SchemaMetaclass | None = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    mode: str = "streaming",
    name: str = "kafka",
    **kwargs: Any,
) -> Table:
    if schema is None:
        schema = sch.schema_from_types(data=str)
    assert topic is not None, "topic= is required"
    servers = rdkafka_settings.get("bootstrap.servers", "")
    upsert = bool(schema.primary_key_columns())
    if servers.startswith("mock://"):
        source: RowSource = _MockKafkaSource(
            MockBroker.get(servers), topic, schema, format, mode
        )
    else:
        from pathway_tpu.io._gated import require

        require("kafka")
        source = _KafkaClientSource(rdkafka_settings, topic, schema, format)
    return input_table(source, schema, name=name, upsert=upsert)


simple_read = read


class _MockKafkaWriter(Writer):
    def __init__(self, broker: MockBroker, topic: str, format: str):
        self.broker = broker
        self.topic = topic
        self.format = format

    def write(self, row: dict, time: int, diff: int) -> None:
        out = {k: fmt_value(v) for k, v in row.items() if k != "id"}
        out["time"] = time
        out["diff"] = diff
        self.broker.produce(self.topic, _json.dumps(out).encode())


def write(
    table: Table,
    rdkafka_settings: dict,
    topic_name: str,
    *,
    format: str = "json",
    **kwargs: Any,
) -> None:
    servers = rdkafka_settings.get("bootstrap.servers", "")
    if servers.startswith("mock://"):
        attach_writer(
            table, _MockKafkaWriter(MockBroker.get(servers), topic_name, format), name="kafka_out"
        )
        return
    from pathway_tpu.io._gated import require

    require("kafka")

    class _ClientWriter(Writer):
        def __init__(self) -> None:
            from kafka import KafkaProducer  # type: ignore[import-not-found]

            self.producer = KafkaProducer(
                bootstrap_servers=rdkafka_settings.get("bootstrap.servers")
            )

        def write(self, row: dict, time: int, diff: int) -> None:
            out = {k: fmt_value(v) for k, v in row.items() if k != "id"}
            out["time"] = time
            out["diff"] = diff
            self.producer.send(topic_name, _json.dumps(out).encode())

        def flush(self) -> None:
            self.producer.flush()

    attach_writer(table, _ClientWriter(), name="kafka_out")
