"""``pw.io`` — connector modules (reference export list
``python/pathway/io/__init__.py:3-65``).

Fully implemented here: fs, csv, jsonlines, plaintext, python, http (REST),
null, sqlite, subscribe.  Service-backed connectors (kafka, postgres, s3,
elasticsearch, ...) expose the reference API surface and raise a clear
error when their client library is absent from the environment (external
services are unreachable in this build's sandbox); their row-parsing logic
routes through the same DictSource/Writer framework, so wiring a client in
is additive.
"""

from __future__ import annotations

import importlib
from typing import Any

from pathway_tpu.io._subscribe import OnChangeCallback, OnFinishCallback, subscribe

_SUBMODULES = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "slack",
    "sqlite",
]


def __getattr__(name: str) -> Any:
    if name in _SUBMODULES:
        return importlib.import_module(f"pathway_tpu.io.{name}")
    raise AttributeError(f"module pathway_tpu.io has no attribute {name!r}")


__all__ = _SUBMODULES + ["subscribe", "OnChangeCallback", "OnFinishCallback"]
