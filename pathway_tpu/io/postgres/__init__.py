"""``pw.io.postgres`` — PostgreSQL sink (reference python/pathway/io/postgres; writer src/connectors/data_storage.rs:1080).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

write = gated_writer("postgres", "psycopg2")

__all__ = ["write"]
