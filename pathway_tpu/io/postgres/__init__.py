"""``pw.io.postgres`` — PostgreSQL sink (reference
``python/pathway/io/postgres``; writer ``PsqlWriter``
``src/connectors/data_storage.rs:1080``; formatters ``PsqlUpdates``
``data_format.rs:1625`` and ``PsqlSnapshot`` ``:1684``).

Two modes, matching the reference:

- :func:`write` — append every update as a row carrying ``time``/``diff``
  columns (the update-stream table form);
- :func:`write_snapshot` — maintain the current snapshot: upserts by
  primary key (``INSERT .. ON CONFLICT .. DO UPDATE``), deletes on
  retraction.

The connection is any DBAPI connection (or zero-arg factory) passed as
``connection=``; with a settings dict, ``psycopg2`` is imported lazily
(absent here — activates when installed).  ``ON CONFLICT`` and qmark/
format paramstyles cover both PostgreSQL and the sqlite used in tests.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer, fmt_value
from pathway_tpu.io._gated import MissingDependency

__all__ = ["write", "write_snapshot"]


def _connect(postgres_settings: dict | None, connection: Any) -> Any:
    if connection is not None:
        # factory vs live connection: sqlite3.Connection is itself
        # callable (executes a statement), so presence of .cursor decides
        if callable(connection) and not hasattr(connection, "cursor"):
            return connection()
        return connection
    try:
        import psycopg2  # type: ignore[import-not-found]
    except ImportError as e:
        raise MissingDependency(
            "psycopg2 is not installed; pass connection= with a DBAPI "
            "connection (or factory) instead"
        ) from e
    return psycopg2.connect(**(postgres_settings or {}))


def _placeholder(conn: Any) -> str:
    mod = type(conn).__module__.split(".")[0]
    if mod == "sqlite3":
        return "?"
    return "%s"


class _PsqlWriter(Writer):
    def __init__(
        self,
        postgres_settings: dict | None,
        connection: Any,
        table_name: str,
        *,
        snapshot_keys: list[str] | None = None,
        max_batch_size: int = 256,
    ):
        self._settings = postgres_settings
        self._connection_arg = connection
        self._conn: Any = None
        self.table_name = table_name
        self.snapshot_keys = snapshot_keys
        self.max_batch_size = max_batch_size
        self._pending = 0

    def _get_conn(self) -> Any:
        if self._conn is None:
            self._conn = _connect(self._settings, self._connection_arg)
        return self._conn

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        conn = self._get_conn()
        ph = _placeholder(conn)
        cur = conn.cursor()
        vals = {k: fmt_value(v) for k, v in row.items() if k != "id"}
        cols = list(vals)
        if self.snapshot_keys is None:
            # update-stream form: every change is an appended row
            cols2 = cols + ["time", "diff"]
            sql = (
                f"INSERT INTO {self.table_name} ({', '.join(cols2)}) "
                f"VALUES ({', '.join([ph] * len(cols2))})"
            )
            cur.execute(sql, [*vals.values(), time, diff])
        elif diff > 0:
            updates = [c for c in cols if c not in self.snapshot_keys]
            sql = (
                f"INSERT INTO {self.table_name} ({', '.join(cols)}) "
                f"VALUES ({', '.join([ph] * len(cols))}) "
                f"ON CONFLICT ({', '.join(self.snapshot_keys)}) DO UPDATE SET "
                + ", ".join(f"{c} = excluded.{c}" for c in updates)
            )
            cur.execute(sql, list(vals.values()))
        else:
            cond = " AND ".join(f"{c} = {ph}" for c in self.snapshot_keys)
            cur.execute(
                f"DELETE FROM {self.table_name} WHERE {cond}",
                [vals[c] for c in self.snapshot_keys],
            )
        self._pending += 1
        if self._pending >= self.max_batch_size:
            conn.commit()
            self._pending = 0

    def flush(self) -> None:
        if self._conn is not None:
            self._conn.commit()
            self._pending = 0

    def close(self) -> None:
        if self._conn is not None:
            self._conn.commit()
            self._conn.close()


def write(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str = "pathway_output",
    *,
    connection: Any = None,
    max_batch_size: int = 256,
    name: str = "postgres_out",
    **kwargs: Any,
) -> None:
    """Append the table's update stream (with time/diff columns)."""
    attach_writer(
        table,
        _PsqlWriter(
            postgres_settings, connection, table_name,
            max_batch_size=max_batch_size,
        ),
        name=name,
    )


def write_snapshot(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str = "pathway_output",
    primary_key: list[str] | None = None,
    *,
    connection: Any = None,
    max_batch_size: int = 256,
    name: str = "postgres_snapshot",
    **kwargs: Any,
) -> None:
    """Maintain the current snapshot keyed by ``primary_key``."""
    if not primary_key:
        raise ValueError("write_snapshot requires primary_key=[...]")
    attach_writer(
        table,
        _PsqlWriter(
            postgres_settings, connection, table_name,
            snapshot_keys=list(primary_key), max_batch_size=max_batch_size,
        ),
        name=name,
    )
