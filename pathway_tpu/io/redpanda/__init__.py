"""``pw.io.redpanda`` — Kafka-compatible API (reference
``python/pathway/io/redpanda``): delegates to ``pw.io.kafka``."""

from pathway_tpu.io.kafka import read, write

__all__ = ["read", "write"]
