"""``pw.io.gdrive`` — Google Drive reader (reference
``python/pathway/io/gdrive/__init__.py``).

Folder listing with pagination, recursive directory walk, glob/size
filters, Google-native document export, incremental streaming sync by
``modifiedTime`` with deleted-file retraction — the same polling tree
diff the reference runs (``_GDriveTree.new_and_changed_files`` /
``removed_files``, reference ``:237-259``).

The Drive v3 service object is injectable (``service=...``): anything
implementing the four calls the connector makes —
``files().list(...).execute()``, ``files().get(...)``,
``files().get_media(...)``, ``files().export_media(...)`` — works, which
is how the connector is tested hermetically (``tests/test_gdrive.py``
drives adds/updates/deletes through a fake service).  Without an
injected service, ``googleapiclient`` + a service-account credentials
file are required, exactly like the reference.
"""

from __future__ import annotations

import fnmatch
import logging
import time
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import coerce_row, input_table
from pathway_tpu.io.python import ConnectorSubject

__all__ = ["read"]

SCOPES = ["https://www.googleapis.com/auth/drive.readonly"]
MIME_TYPE_FOLDER = "application/vnd.google-apps.folder"
FILE_FIELDS = (
    "id, name, mimeType, parents, modifiedTime, thumbnailLink, "
    "lastModifyingUser, trashed, size"
)

STATUS_DOWNLOADED = "downloaded"
STATUS_SIZE_LIMIT_EXCEEDED = "size_limit_exceeded"
STATUS_SYMLINKS_NOT_SUPPORTED = "symlinks_not_supported"

#: Google-native docs have no binary content; they export to office
#: formats (reference DEFAULT_MIME_TYPE_MAPPING)
DEFAULT_MIME_TYPE_MAPPING: dict[str, str] = {
    "application/vnd.google-apps.document": (
        "application/vnd.openxmlformats-officedocument."
        "wordprocessingml.document"
    ),
    "application/vnd.google-apps.spreadsheet": (
        "application/vnd.openxmlformats-officedocument."
        "spreadsheetml.sheet"
    ),
    "application/vnd.google-apps.presentation": (
        "application/vnd.openxmlformats-officedocument."
        "presentationml.presentation"
    ),
}

GDriveFile = dict

_logger = logging.getLogger("pathway_tpu.io.gdrive")


_ERROR_TYPES: tuple | None = None


def _http_error_types() -> tuple:
    """Exception types treated as transient Drive API failures (computed
    once — a failed googleapiclient import is not negatively cached by
    Python, and this runs on every poll of every file)."""
    global _ERROR_TYPES
    if _ERROR_TYPES is None:
        try:
            from googleapiclient.errors import HttpError  # type: ignore

            _ERROR_TYPES = (HttpError, ConnectionError, TimeoutError)
        except ImportError:
            _ERROR_TYPES = (ConnectionError, TimeoutError)
    return _ERROR_TYPES


def extend_metadata(metadata: GDriveFile) -> GDriveFile:
    metadata = add_url(metadata)
    metadata = add_path(metadata)
    metadata = add_seen_at(metadata)
    metadata = add_status(metadata)
    return metadata


def add_seen_at(metadata: GDriveFile) -> GDriveFile:
    metadata["seen_at"] = int(time.time())
    return metadata


def add_url(metadata: GDriveFile) -> GDriveFile:
    id = metadata["id"]
    metadata["url"] = f"https://drive.google.com/file/d/{id}/"
    return metadata


def add_path(metadata: GDriveFile) -> GDriveFile:
    metadata["path"] = metadata["name"]
    return metadata


def add_status(metadata: GDriveFile) -> GDriveFile:
    metadata["status"] = STATUS_DOWNLOADED
    return metadata


class _GDriveClient:
    """Listing + download over an injectable Drive v3 service object."""

    def __init__(
        self,
        service: Any,
        object_size_limit: int | None = None,
        file_name_pattern: list | str | None = None,
        injected: bool = False,
    ) -> None:
        self.drive = service
        self.export_type_mapping = DEFAULT_MIME_TYPE_MAPPING
        self.object_size_limit = object_size_limit
        self.file_name_pattern = file_name_pattern
        #: injected services serve payloads via request.execute();
        #: googleapiclient requests stream through MediaIoBaseDownload.
        #: Keyed on HOW the service arrived, not on which packages are
        #: importable — a fake must keep working when googleapiclient
        #: happens to be installed.
        self.injected = injected

    def _query(self, q: str = "") -> list:
        """files().list with nextPageToken pagination (reference _query)."""
        items: list = []
        page_token = None
        while True:
            response = (
                self.drive.files()
                .list(
                    q=q,
                    pageSize=10,
                    supportsAllDrives=True,
                    includeItemsFromAllDrives=True,
                    fields=f"nextPageToken, files({FILE_FIELDS})",
                    pageToken=page_token,
                )
                .execute()
            )
            items.extend(response.get("files", []))
            page_token = response.get("nextPageToken", None)
            if page_token is None:
                break
        return items

    def _get(self, file_id: str) -> GDriveFile | None:
        """Metadata for one object, or None when gone/trashed."""
        errors = _http_error_types()
        try:
            file = (
                self.drive.files()
                .get(
                    fileId=file_id,
                    fields=FILE_FIELDS,
                    supportsAllDrives=True,
                )
                .execute()
            )
        except errors as e:
            _logger.warning("cannot stat gdrive object %s: %s", file_id, e)
            return None
        if file is None or file.get("trashed"):
            return None
        return file

    def _ls(self, id: str) -> list[GDriveFile]:
        """Recursive listing rooted at a folder or single-file id."""
        root = self._get(id)
        if root is None:
            return []
        if root["mimeType"] != MIME_TYPE_FOLDER:
            return [extend_metadata(root)]
        return self._ls_folder(id)

    def _ls_folder(self, folder_id: str) -> list[GDriveFile]:
        # the parent listing already carried each subfolder's metadata
        # (and the query filters trashed), so recursion lists children
        # directly — no per-folder re-stat against the rate limit
        subitems = self._query(f"'{folder_id}' in parents and trashed=false")
        files = [i for i in subitems if i["mimeType"] != MIME_TYPE_FOLDER]
        files = self._apply_filters(files)
        out = [extend_metadata(file) for file in files]
        for subdir in (i for i in subitems if i["mimeType"] == MIME_TYPE_FOLDER):
            out.extend(self._ls_folder(subdir["id"]))
        return out

    def _apply_filters(self, files: list[GDriveFile]) -> list[GDriveFile]:
        return self._filter_by_pattern(self._filter_by_size(files))

    def _filter_by_pattern(self, files: list[GDriveFile]) -> list[GDriveFile]:
        pattern = self.file_name_pattern
        if pattern is None:
            return files
        patterns = [pattern] if isinstance(pattern, str) else list(pattern)
        return [
            f
            for f in files
            if any(fnmatch.fnmatch(f["name"], p) for p in patterns)
        ]

    def _filter_by_size(self, files: list[GDriveFile]) -> list[GDriveFile]:
        if self.object_size_limit is None:
            return files
        # folder listings DROP oversized files (reference _filter_by_size,
        # :148-168); only a single-file root reaches download()'s
        # size_limit_exceeded marking.  Size-less objects (Google-native
        # docs) always pass.
        return [
            f
            for f in files
            if f.get("size") is None
            or int(f["size"]) <= self.object_size_limit
        ]

    def _prepare_download_request(self, file: GDriveFile) -> Any:
        export_type = self.export_type_mapping.get(file["mimeType"])
        if export_type is not None:
            return self.drive.files().export_media(
                fileId=file["id"], mimeType=export_type
            )
        return self.drive.files().get_media(fileId=file["id"])

    def download(self, file: GDriveFile) -> bytes | None:
        is_symlink = (
            file.get("size") is None
            and file["mimeType"] not in self.export_type_mapping
        )
        is_too_large = (
            self.object_size_limit is not None
            and int(file.get("size", "0")) > self.object_size_limit
        )
        if is_symlink:
            file["status"] = STATUS_SYMLINKS_NOT_SUPPORTED
            return b""
        if is_too_large:
            file["status"] = STATUS_SIZE_LIMIT_EXCEEDED
            return b""
        errors = _http_error_types()
        try:
            request = self._prepare_download_request(file)
            if self.injected:
                return request.execute()
            import io as _io

            from googleapiclient.http import (  # type: ignore
                MediaIoBaseDownload,
            )

            response = _io.BytesIO()
            downloader = MediaIoBaseDownload(response, request)
            done = False
            while not done:
                _progress, done = downloader.next_chunk()
            return response.getvalue()
        except errors as e:
            _logger.warning(
                "cannot fetch gdrive file %s: %s", file["id"], e
            )
            file["status"] = "download_error"
            return None

    def tree(self, root_id: str) -> "_GDriveTree":
        return _GDriveTree({file["id"]: file for file in self._ls(root_id)})


@dataclass(frozen=True)
class _GDriveTree:
    """One poll's snapshot; diffs against the previous poll drive the
    streaming upserts/retractions (reference _GDriveTree:237-259)."""

    files: dict[str, GDriveFile]

    def _diff(self, other: "_GDriveTree") -> list[GDriveFile]:
        return [f for f in self.files.values() if f["id"] not in other.files]

    def _modified_files(self, previous: "_GDriveTree") -> list[GDriveFile]:
        return [
            f
            for f in self.files.values()
            if (prev := previous.files.get(f["id"])) is not None
            and f["modifiedTime"] > prev["modifiedTime"]
        ]

    def removed_files(self, previous: "_GDriveTree") -> list[GDriveFile]:
        return previous._diff(self)

    def new_and_changed_files(self, previous: "_GDriveTree") -> list[GDriveFile]:
        return self._diff(previous) + self._modified_files(previous)


class _GDriveSubject(ConnectorSubject):
    """Polling subject: rows are keyed by the Drive file id, so a
    re-download of a changed file overwrites (upsert session) and a
    vanished id retracts."""

    def __init__(
        self,
        *,
        service_factory: Callable[[], Any],
        root: str,
        refresh_interval: float,
        mode: str,
        with_metadata: bool,
        object_size_limit: int | None,
        file_name_pattern: list | str | None,
        service_injected: bool = False,
    ) -> None:
        super().__init__(datasource_name="gdrive")
        assert mode in ("streaming", "static")
        self._service_factory = service_factory
        self._root = root
        self._refresh_interval = refresh_interval
        self._mode = mode
        self._append_metadata = with_metadata
        self._object_size_limit = object_size_limit
        self._file_name_pattern = file_name_pattern
        self._service_injected = service_injected

    def run(self) -> None:
        client = _GDriveClient(
            self._service_factory(),
            self._object_size_limit,
            self._file_name_pattern,
            injected=self._service_injected,
        )
        errors = _http_error_types()
        prev = _GDriveTree({})
        while True:
            try:
                tree = client.tree(self._root)
            except errors as e:
                _logger.error(
                    "failed to query gdrive: %s; retrying in %ss",
                    e,
                    self._refresh_interval,
                )
            else:
                failed: set[str] = set()
                for file in tree.removed_files(prev):
                    self.remove(file)
                for file in tree.new_and_changed_files(prev):
                    payload = client.download(file)
                    if payload is not None:
                        self.upsert(file, payload)
                    else:
                        failed.add(file["id"])
                self.commit()
                if self._mode == "static":
                    return
                # a transiently failed download must NOT enter prev: the
                # file would read as already-synced and never retry
                prev = _GDriveTree(
                    {id: f for id, f in tree.files.items() if id not in failed}
                )
            # responsive sleep: a stopping scheduler must not wait out a
            # long refresh interval
            deadline = time.monotonic() + self._refresh_interval
            while time.monotonic() < deadline:
                if self.stopped:
                    return
                time.sleep(min(0.1, self._refresh_interval))

    def _row(self, file: GDriveFile, payload: bytes) -> dict:
        values: dict[str, Any] = {"data": payload}
        if self._append_metadata:
            values["_metadata"] = dict(file)
        return values

    def upsert(self, file: GDriveFile, payload: bytes) -> None:
        key = K.ref_scalar(file["id"])
        self._events.add(key, coerce_row(self._row(file, payload), self._schema))

    def remove(self, file: GDriveFile) -> None:
        key = K.ref_scalar(file["id"])
        self._events.remove(key, coerce_row(self._row(file, b""), self._schema))


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    refresh_interval: float = 30,
    service_user_credentials_file: str | None = None,
    with_metadata: bool = False,
    file_name_pattern: list | str | None = None,
    service: Any = None,
    name: str = "gdrive",
    **kwargs: Any,
) -> Table:
    """Read a Google Drive directory or file as a table with one ``data``
    column of file payloads (reference ``pw.io.gdrive.read``,
    ``python/pathway/io/gdrive/__init__.py:336``).

    Args:
        object_id: id of a directory or file; directories scan recursively.
        mode: "streaming" polls for adds/updates/deletes every
            ``refresh_interval`` seconds; "static" ingests once.
        object_size_limit: max file size in bytes, or None.  Oversized
            files are dropped from folder listings (reference
            ``_filter_by_size``); a single-file ``object_id`` over the
            limit yields an empty payload with
            ``status == "size_limit_exceeded"`` in the metadata.
        refresh_interval: seconds between scans in streaming mode.
        service_user_credentials_file: Google service-account JSON file
            (requires ``googleapiclient``).
        with_metadata: add a ``_metadata`` column (id, name, mimeType,
            modifiedTime, url, path, status, ...).
        file_name_pattern: glob pattern (or list) filtering by file name.
        service: injectable Drive v3 service object — any object with the
            ``files().list/get/get_media/export_media`` surface; replaces
            the credentials flow entirely (tests, alternative transports).
    """
    if mode not in ("streaming", "static"):
        raise ValueError(f"Unrecognized connector mode: {mode}")
    if service is not None:
        service_factory = lambda: service  # noqa: E731
    elif service_user_credentials_file is not None:

        def service_factory() -> Any:
            try:
                from google.oauth2.service_account import (  # type: ignore
                    Credentials as ServiceCredentials,
                )
                from googleapiclient.discovery import build  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "pw.io.gdrive.read needs googleapiclient + "
                    "google-auth for the credentials flow; alternatively "
                    "pass service=... with a Drive-v3-compatible object"
                ) from e
            credentials = ServiceCredentials.from_service_account_file(
                service_user_credentials_file, scopes=SCOPES
            )
            return build(
                "drive", "v3", credentials=credentials, num_retries=3
            )

    else:
        raise ValueError(
            "pw.io.gdrive.read requires service_user_credentials_file "
            "(live Google API) or service=... (injected client)"
        )
    if with_metadata:
        schema = sch.schema_from_types(data=bytes, _metadata=dict)
    else:
        schema = sch.schema_from_types(data=bytes)
    subject = _GDriveSubject(
        service_factory=service_factory,
        service_injected=service is not None,
        root=object_id,
        refresh_interval=refresh_interval,
        mode=mode,
        with_metadata=with_metadata,
        object_size_limit=object_size_limit,
        file_name_pattern=file_name_pattern,
    )
    from pathway_tpu.io.python import _SubjectAdapter

    adapter = _SubjectAdapter(subject, schema)
    return input_table(
        adapter,
        schema,
        name=name,
        # streaming re-downloads overwrite by file id (reference
        # SessionType.UPSERT); static ingests exactly once (NATIVE)
        upsert=mode == "streaming",
    )
