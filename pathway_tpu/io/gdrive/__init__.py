"""``pw.io.gdrive`` — Google Drive reader (reference python/pathway/io/gdrive).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

read = gated_reader("gdrive", "google.oauth2")

__all__ = ["read"]
