"""``pw.io.gdrive`` — Google Drive reader (reference
``python/pathway/io/gdrive``).

Intentionally gated, not implemented: the reference connector is a thin
loop over the authenticated Google Drive v3 REST client
(``files().list`` by folder id + ``files().get_media`` downloads), and
every interesting behavior — OAuth2 service-account flow, token refresh,
export of Google-native docs, 404-on-revoked-share handling — lives
inside ``googleapiclient`` + live Google endpoints that are unreachable
from this environment (zero egress, no credentials).  A fake-client
"implementation" would test nothing beyond what ``pw.io.pyfilesystem``
(which accepts ANY PyFilesystem, including a Drive-backed one) and
``pw.io.s3``'s injectable-client pattern already prove.  The API
surface matches the reference so code written against it ports; calls
raise ``MissingDependency`` until ``googleapiclient`` is installed.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader

read = gated_reader("gdrive", "googleapiclient")

__all__ = ["read"]
