"""``pw.io.jsonlines`` — JSON Lines file connector (reference
``python/pathway/io/jsonlines``; engine parser ``JsonLinesParser``
``src/connectors/data_format.rs:1439``)."""

from __future__ import annotations

import json
import os
from typing import Any

from pathway_tpu.internals import native as _native
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import (
    LazyFileWriter,
    attach_writer,
    fmt_value,
    input_table,
)
from pathway_tpu.io.fs import _FilesSource, _list_files

__all__ = ["read", "write"]


def read(
    path: str | os.PathLike,
    *,
    schema: sch.SchemaMetaclass | None = None,
    mode: str = "streaming",
    json_field_paths: dict[str, str] | None = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str = "jsonlines",
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if schema is None:
        schema = sch.schema_from_types(data=dict)

    def parse_line(line: str) -> dict[str, Any] | None:
        line = line.strip()
        if not line:
            return None
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            return None
        if not isinstance(obj, dict):
            return None  # valid JSON but not an object: skip
        if json_field_paths:
            for col, jpath in json_field_paths.items():
                cur: Any = obj
                for part in jpath.strip("/").split("/"):
                    if isinstance(cur, dict):
                        cur = cur.get(part)
                    else:
                        cur = None
                        break
                obj[col] = cur
        return obj

    def parse_block(data: bytes) -> list[dict] | None:
        """Block fast path: join a block of complete JSONL lines into ONE
        JSON array and parse it with a single C-level ``json.loads``
        (~7x the per-line loop; JSONL guarantees raw newlines only appear
        as separators — inside strings they are escaped).  Any malformed
        line fails the whole-block parse, falling back to the per-line
        parser which skips bad rows individually."""
        if json_field_paths:
            return None
        # plain `if ln` instead of `if ln.strip()`: a per-line strip costs
        # ~10% of the whole parse; whitespace-only lines are rare enough
        # that letting them fail the block parse (-> per-line fallback)
        # is the better trade
        lines = [ln for ln in data.split(b"\n") if ln]
        if not lines:
            return []
        try:
            rows = json.loads(b"[" + b",".join(lines) + b"]")
        except ValueError:
            # JSONDecodeError AND UnicodeDecodeError (invalid UTF-8 bytes)
            # are both ValueError; the per-line fallback skips bad rows
            # individually with errors="replace"
            return None
        native = _native.load()
        if native is not None:
            if not native.all_dicts(rows):
                return None  # non-object lines: per-line path skips them
        elif not all(isinstance(r, dict) for r in rows):
            return None
        return rows

    # columnar frame parsing is sound only for flat objects mapped
    # one-to-one onto the schema — json_field_paths rewrites rows in
    # Python, so it stays on the row path
    frame_plan = None
    if not json_field_paths:
        from pathway_tpu.io._connector import _schema_plans

        frame_plan = _schema_plans(schema)[1]

    source = _FilesSource(
        str(path), schema, parse_line=parse_line, parse_block=parse_block,
        frame_plan=frame_plan, mode=mode,
        with_metadata=with_metadata, tag=f"jsonlines:{path}",
    )
    return input_table(source, schema, name=name, persistent_id=persistent_id)


class _JsonLinesWriter(LazyFileWriter):
    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        out = {k: fmt_value(v) for k, v in row.items() if k != "id"}
        out["time"] = time
        out["diff"] = diff
        self._file().write(json.dumps(out) + "\n")



def write(table: Table, filename: str | os.PathLike, *, name: str = "jsonlines_out", **kwargs: Any) -> None:
    attach_writer(table, _JsonLinesWriter(str(filename)), name=name)
