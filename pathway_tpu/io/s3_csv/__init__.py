"""``pw.io.s3_csv`` — S3 CSV reader (reference ``python/pathway/io/s3_csv``):
``pw.io.s3.read`` preset to the CSV format."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io import s3 as _s3
from pathway_tpu.io.s3 import AwsS3Settings

__all__ = ["read", "AwsS3Settings"]


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    schema: Any = None,
    csv_settings: Any = None,
    mode: str = "streaming",
    **kwargs: Any,
) -> Table:
    return _s3.read(
        path,
        aws_s3_settings=aws_s3_settings,
        format="csv",
        schema=schema,
        csv_settings=csv_settings,
        mode=mode,
        name=kwargs.pop("name", "s3_csv"),
        **kwargs,
    )
