"""``pw.io.s3_csv`` — S3 CSV reader (reference python/pathway/io/s3_csv).

Delegates settings/transport to ``pw.io.s3``.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import require
from pathway_tpu.io.s3 import AwsS3Settings


def read(path: str, *args: Any, format: str = "csv", **kwargs: Any) -> Any:
    require("s3fs")
    raise NotImplementedError(
        "pw.io.s3_csv.read: s3fs present but transport not wired in this build"
    )


__all__ = ["read", "AwsS3Settings"]
