"""``pw.io.python`` — custom Python connectors.

Capability parity with reference ``python/pathway/io/python/__init__.py``
(``ConnectorSubject`` ``:49-308``): subclass :class:`ConnectorSubject`,
override ``run()``, push rows with ``next``/``next_json``/``next_str``/
``next_bytes``, delete with ``_remove``, cut epochs with ``commit()``.
"""

from __future__ import annotations

import json as _json
import threading
from typing import Any

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, coerce_row, input_table, key_for_row

__all__ = ["ConnectorSubject", "read"]


class ConnectorSubject:
    """Base class for custom streaming sources."""

    def __init__(self, datasource_name: str = "python") -> None:
        self._events: Any = None
        self._schema: sch.SchemaMetaclass | None = None
        self._seq = 0
        self._name = datasource_name
        self._deletions_enabled = True

    # -- user API -----------------------------------------------------------
    def run(self) -> None:  # pragma: no cover
        raise NotImplementedError

    def next(self, **kwargs: Any) -> None:
        self._add_values(kwargs)

    def next_json(self, message: dict | str | bytes) -> None:
        if isinstance(message, (str, bytes)):
            message = _json.loads(message)
        self._add_values(dict(message))

    def next_str(self, message: str) -> None:
        self._add_values({"data": message})

    def next_bytes(self, message: bytes) -> None:
        self._add_values({"data": message})

    def commit(self) -> None:
        if self._events is not None:
            self._events.commit()

    def close(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    @property
    def stopped(self) -> bool:
        """True once the scheduler is shutting down; long-running ``run()``
        loops should poll this and return."""
        return self._events is not None and self._events.stopped

    # -- plumbing -----------------------------------------------------------
    def _add_values(self, values: dict[str, Any]) -> None:
        assert self._schema is not None and self._events is not None
        key = self._key_of(values)
        self._events.add(key, coerce_row(values, self._schema))

    def _remove(self, values: dict[str, Any]) -> None:
        assert self._schema is not None and self._events is not None
        key = self._key_of(values)
        self._events.remove(key, coerce_row(values, self._schema))

    def _key_of(self, values: dict[str, Any]) -> K.Pointer:
        pk = self._schema.primary_key_columns()  # type: ignore[union-attr]
        if pk:
            return K.ref_scalar(*[values[c] for c in pk])
        self._seq += 1
        return K.ref_scalar("__py_connector__", id(self), self._seq)


class _SubjectAdapter(RowSource):
    def __init__(self, subject: ConnectorSubject, schema: sch.SchemaMetaclass):
        self.subject = subject
        self.schema = schema
        # forward the wrapped subject's replay contract: supervised
        # restart and persistence resume inspect ``node.subject``, which
        # is this adapter, not the user's ConnectorSubject
        self.deterministic_replay = bool(
            getattr(subject, "deterministic_replay", False)
        )
        # distribution facts: a python connector runs ONE reader thread,
        # so it is single-owner and order-preserving unless the wrapped
        # subject declares otherwise (analysis/distribution.py, PW-X001)
        self.partitioning = getattr(subject, "partitioning", "single")
        self.order_preserving = bool(getattr(subject, "order_preserving", True))
        hook = getattr(subject, "on_persistence_resume", None)
        if hook is not None:
            self.on_persistence_resume = hook

    def run(self, events: Any) -> None:
        self.subject._events = events
        self.subject._schema = self.schema
        try:
            self.subject.run()
        finally:
            self.subject.on_stop()
            self.subject.close()


def read(
    subject: ConnectorSubject,
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = None,
    name: str = "python",
    persistent_id: str | None = None,
    recovery_policy: Any = None,
    on_overflow: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a stream produced by a :class:`ConnectorSubject`.

    ``recovery_policy`` (a
    :class:`~pathway_tpu.internals.resilience.ConnectorRecoveryPolicy`)
    opts the source into supervised restart with backoff; without one a
    reader failure closes the stream after a single attempt.
    ``on_overflow`` picks this source's full-ingest-buffer behaviour
    (``"pause"``/``"shed_oldest"``/``"fail"``)."""
    adapter = _SubjectAdapter(subject, schema)
    upsert = bool(schema.primary_key_columns())
    return input_table(
        adapter,
        schema,
        name=name,
        upsert=upsert,
        persistent_id=persistent_id,
        recovery_policy=recovery_policy,
        on_overflow=on_overflow,
    )
