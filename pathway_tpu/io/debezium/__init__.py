"""``pw.io.debezium`` — Debezium CDC streams (reference
``python/pathway/io/debezium``; parser ``DebeziumMessageParser``
``src/connectors/data_format.rs:1053``).

Consumes CDC envelopes from a Kafka topic (real ``kafka-python`` broker or
the in-process ``mock://`` broker) and maps them onto the engine's upsert
input session (reference ``SessionType::Upsert``):

- ``op`` in (``r`` read-snapshot, ``c`` create, ``u`` update): upsert
  ``payload.after`` under the primary-key columns;
- ``op`` = ``d`` (delete): remove by ``payload.before``'s key.

Both the flat Debezium JSON envelope and the ``schema``/``payload``
wrapped form are accepted; MongoDB's variant (after/patch as embedded
JSON strings) is unwrapped too.
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, coerce_row, input_table

__all__ = ["read", "DB_TYPE_POSTGRES", "DB_TYPE_MONGODB"]

DB_TYPE_POSTGRES = "postgres"
DB_TYPE_MONGODB = "mongodb"


def _unwrap(raw: bytes) -> dict | None:
    try:
        msg = _json.loads(raw.decode())
    except Exception:
        return None
    if not isinstance(msg, dict):
        return None
    payload = msg.get("payload", msg)
    return payload if isinstance(payload, dict) else None


def _row_from(payload_side: Any) -> dict | None:
    if isinstance(payload_side, str):  # MongoDB embeds JSON strings
        try:
            payload_side = _json.loads(payload_side)
        except Exception:
            return None
    return payload_side if isinstance(payload_side, dict) else None


class _DebeziumSource(RowSource):
    """Kafka-topic reader emitting upsert/delete events from CDC
    envelopes.  Keys come from the schema's primary-key columns."""

    deterministic_replay = False  # live CDC position; broker tracks offsets

    def __init__(
        self,
        rdkafka_settings: dict,
        topic: str,
        schema: sch.SchemaMetaclass,
        *,
        poll_timeout: float = 0.5,
    ):
        self.rdkafka_settings = rdkafka_settings
        self.topic = topic
        self.schema = schema
        self.poll_timeout = poll_timeout
        self._resume = 0

    def on_persistence_resume(self, n_events: int) -> None:
        self._resume = n_events

    def _key(self, values: dict) -> Any:
        pk = self.schema.primary_key_columns()
        cols = pk or list(self.schema.__columns__)
        return ref_scalar(*[values.get(c) for c in cols])

    def _consume_mock(self, events: Any, broker: Any) -> None:
        offset = 0
        emitted = 0
        while True:
            msgs = broker.consume_from(self.topic, offset, self.poll_timeout)
            for _k, raw in msgs:
                offset += 1
                if self._emit(events, raw):
                    emitted += 1
            if msgs:
                events.commit()
            if broker.is_closed(self.topic) and offset >= len(
                broker.topics[self.topic]
            ):
                return
            if events.stopped:
                return

    def _emit(self, events: Any, raw: bytes) -> bool:
        payload = _unwrap(raw)
        if payload is None:
            return False
        op = payload.get("op")
        if op in ("r", "c", "u"):
            row = _row_from(payload.get("after"))
            if row is None:
                return False
            if self._resume > 0:
                self._resume -= 1
                return False
            events.add(self._key(row), coerce_row(row, self.schema))
            return True
        if op == "d":
            row = _row_from(payload.get("before"))
            if row is None:
                return False
            if self._resume > 0:
                self._resume -= 1
                return False
            events.remove(self._key(row), coerce_row(row, self.schema))
            return True
        return False

    def run(self, events: Any) -> None:
        servers = str(self.rdkafka_settings.get("bootstrap.servers", ""))
        if servers.startswith("mock://"):
            from pathway_tpu.io.kafka import MockBroker

            self._consume_mock(events, MockBroker.get(servers))
            return
        from kafka import KafkaConsumer  # type: ignore[import-not-found]

        group_id = self.rdkafka_settings.get("group.id")
        if group_id:
            # committed group offsets: the broker resumes PAST consumed
            # history, so nothing is redelivered — an armed resume skip
            # would silently drop the first N FRESH CDC events.  The skip
            # only applies to transports that actually replay from the
            # start (mock broker, or no consumer group below).
            self._resume = 0
        consumer = KafkaConsumer(
            self.topic,
            bootstrap_servers=servers,
            group_id=group_id,
            auto_offset_reset=self.rdkafka_settings.get(
                "auto.offset.reset", "earliest"
            ),
        )
        try:
            emitted = False
            while not events.stopped:
                polled = consumer.poll(timeout_ms=int(self.poll_timeout * 1000))
                for records in polled.values():
                    for record in records:
                        if self._emit(events, record.value):
                            emitted = True
                if emitted:
                    events.commit()
                    emitted = False
        finally:
            consumer.close()


def read(
    rdkafka_settings: dict,
    topic_name: str,
    *,
    schema: sch.SchemaMetaclass,
    db_type: str = DB_TYPE_POSTGRES,
    autocommit_duration_ms: int | None = 1500,
    name: str = "debezium",
    **kwargs: Any,
) -> Table:
    """CDC table mirroring the upstream database (upsert semantics)."""
    src = _DebeziumSource(rdkafka_settings, topic_name, schema)
    return input_table(src, schema, name=name, upsert=True)
