"""``pw.io.csv`` — CSV connector (reference ``python/pathway/io/csv``;
engine DSV parser ``src/connectors/data_format.rs:500``)."""

from __future__ import annotations

import csv as _csv
import io as _io
import os
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import (
    LazyFileWriter,
    attach_writer,
    fmt_value,
    input_table,
)
from pathway_tpu.io.fs import _FilesSource

__all__ = ["read", "write", "CsvParserSettings"]


class CsvParserSettings:
    def __init__(
        self,
        delimiter: str = ",",
        quote: str = '"',
        escape: str | None = None,
        enable_double_quote_escapes: bool = True,
        enable_quoting: bool = True,
        comment_character: str | None = None,
    ):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.enable_double_quote_escapes = enable_double_quote_escapes
        self.enable_quoting = enable_quoting
        self.comment_character = comment_character

    def reader_kwargs(self) -> dict[str, Any]:
        return {
            "delimiter": self.delimiter,
            "quotechar": self.quote,
            "escapechar": self.escape,
            "doublequote": self.enable_double_quote_escapes,
            "quoting": _csv.QUOTE_MINIMAL if self.enable_quoting else _csv.QUOTE_NONE,
        }


def read(
    path: str | os.PathLike,
    *,
    schema: sch.SchemaMetaclass | None = None,
    csv_settings: CsvParserSettings | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str = "csv",
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    settings = csv_settings or CsvParserSettings()
    if schema is None:
        raise ValueError("pw.io.csv.read requires schema=")

    def parser_factory(fp: str):
        # header state is per file — each file starts with its own header row
        state: dict[str, list[str] | None] = {"header": None}

        def parse_line(line: str) -> dict | None:
            line = line.rstrip("\n").rstrip("\r")
            if not line:
                return None
            if settings.comment_character and line.startswith(settings.comment_character):
                return None
            row = next(_csv.reader(_io.StringIO(line), **settings.reader_kwargs()))
            if state["header"] is None:
                state["header"] = row
                return None
            return dict(zip(state["header"], row))

        return parse_line

    src = _FilesSource(
        str(path),
        schema,
        parser_factory=parser_factory,
        mode=mode,
        with_metadata=with_metadata,
        tag=f"csv:{path}",
    )
    return input_table(src, schema, name=name, persistent_id=persistent_id)


class _CsvWriter(LazyFileWriter):
    _open_newline = ""

    def __init__(self, path: str):
        super().__init__(path)
        self._writer: Any = None

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        out = {k: fmt_value(v) for k, v in row.items() if k != "id"}
        out["time"] = time
        out["diff"] = diff
        if self._writer is None:
            self._writer = _csv.DictWriter(self._file(), fieldnames=list(out.keys()))
            self._writer.writeheader()
        self._writer.writerow(out)



def write(table: Table, filename: str | os.PathLike, **kwargs: Any) -> None:
    attach_writer(table, _CsvWriter(str(filename)))
