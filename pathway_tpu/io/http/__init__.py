"""``pw.io.http`` — REST ingress/egress.

Capability parity with reference ``python/pathway/io/http/_server.py``:
``rest_connector(...) -> (Table, response_writer)`` (``:624``),
``PathwayWebserver`` (aiohttp + OpenAPI docs, ``:329``),
``RestServerSubject`` (``:490``).  Each HTTP request becomes a row; the
response is resolved when the paired response table produces the row's
result (future-per-key, exactly the reference's mechanism).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, coerce_row, fmt_value, input_table
from pathway_tpu.io._subscribe import subscribe

__all__ = ["rest_connector", "PathwayWebserver", "RetryLater"]

logger = logging.getLogger("pathway_tpu.http")


class RetryLater(Exception):
    """Request shed by admission control before entering the engine.

    The ingress maps it to HTTP 429 with a ``Retry-After`` header — the
    client is told WHEN capacity is expected back instead of having its
    request buffered into an unbounded queue (see
    ``pathway_tpu/serving/admission.py``)."""

    def __init__(self, retry_after: float = 1.0, reason: str = "overloaded"):
        super().__init__(reason)
        self.retry_after = max(0.0, float(retry_after))
        self.reason = reason


class PathwayWebserver:
    """One aiohttp server shared by any number of routes (reference
    ``PathwayWebserver``).  Runs on its own thread + event loop."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: dict[tuple[str, str], Callable] = {}
        self._openapi_paths: dict[str, Any] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def register(self, route: str, methods: tuple[str, ...], handler: Callable, doc: Any = None) -> None:
        for m in methods:
            self._routes[(m.upper(), route)] = handler
        if doc is not None:
            self._openapi_paths[route] = doc

    def openapi_description_json(self) -> dict:
        return {
            "openapi": "3.0.3",
            "info": {"title": "pathway_tpu app", "version": "1.0"},
            "paths": self._openapi_paths,
        }

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(target=self._serve, daemon=True)
            self._thread.start()
        self._started.wait(timeout=10)

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()

        async def dispatch(request: "web.Request") -> "web.Response":
            handler = self._routes.get((request.method, request.path))
            if handler is None:
                return web.json_response({"error": "not found"}, status=404)
            try:
                payload: dict[str, Any] = {}
                if request.can_read_body:
                    text = await request.text()
                    if text:
                        payload = json.loads(text)
                payload.update(request.query)
                result = await handler(payload, request)
                if isinstance(result, web.Response):
                    return result
                return web.json_response(result, dumps=lambda o: json.dumps(o, default=str))
            except RetryLater as e:
                # load shed: bounded queues + explicit backpressure, never
                # a silent drop or an unbounded buffer
                import math

                return web.json_response(
                    {"error": e.reason, "retry_after": e.retry_after},
                    status=429,
                    headers={"Retry-After": str(max(1, math.ceil(e.retry_after)))},
                )
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            except Exception as e:  # noqa: BLE001
                logger.exception("handler failed")
                return web.json_response({"error": repr(e)}, status=500)

        async def docs(_request: "web.Request") -> "web.Response":
            return web.json_response(self.openapi_description_json())

        app.router.add_route("*", "/_schema", docs)
        app.router.add_route("*", "/{tail:.*}", dispatch)

        async def start() -> None:
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._started.set()

        loop.run_until_complete(start())
        loop.run_forever()


class RestServerSubject(RowSource):
    """Bridges HTTP requests into the engine stream (reference
    ``RestServerSubject`` ``io/http/_server.py:490``)."""

    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: tuple[str, ...],
        schema: sch.SchemaMetaclass,
        delete_completed_queries: bool,
        request_validator: Callable | None = None,
        admission: Any = None,
        tenant_field: str = "tenant",
    ):
        self.webserver = webserver
        self.route = route
        self.methods = methods
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        #: admission controller (serving/admission.py contract: ``admit(
        #: tenant, route=...) -> ticket`` raising :class:`RetryLater` on
        #: shed, ticket released when the request leaves the system) —
        #: None keeps the legacy unbounded ingress
        self.admission = admission
        self.tenant_field = tenant_field
        self.futures: dict[K.Pointer, asyncio.Future] = {}
        self._seq = 0
        self._events: Any = None
        self._closed = threading.Event()

    def run(self, events: Any) -> None:
        self._events = events
        doc = {
            "post": {
                "requestBody": {
                    "content": {
                        "application/json": {
                            "schema": {
                                "type": "object",
                                "properties": {
                                    n: {"type": "string"}
                                    for n in self.schema.column_names()
                                },
                            }
                        }
                    }
                },
                "responses": {"200": {"description": "result"}},
            }
        }
        self.webserver.register(self.route, self.methods, self._handle, doc)
        self.webserver._ensure_started()
        # REST source stays open for the lifetime of the run (or until the
        # scheduler shuts down)
        while not self._closed.is_set() and not events.stopped:
            self._closed.wait(timeout=0.25)

    async def _handle(self, payload: dict[str, Any], request: Any) -> Any:
        if self.request_validator is not None:
            maybe_error = self.request_validator(payload)
            if maybe_error is not None:
                raise ValueError(str(maybe_error))
        ticket = None
        if self.admission is not None:
            # bounded ingress: admit or shed BEFORE the row enters the
            # engine; the ticket holds one slot of the tenant's bounded
            # queue until the response resolves (raises RetryLater)
            tenant = str(payload.get(self.tenant_field) or "default")
            ticket = self.admission.admit(tenant, route=self.route)
        try:
            self._seq += 1
            key = K.ref_scalar("__rest__", id(self), self._seq)
            row = coerce_row(payload, self.schema)
            loop = asyncio.get_running_loop()
            future: asyncio.Future = loop.create_future()
            self.futures[key] = future
            self._events.add(key, row)
            self._events.commit()
            try:
                result = await asyncio.wait_for(future, timeout=120)
            finally:
                self.futures.pop(key, None)
                if self.delete_completed_queries:
                    self._events.remove(key, row)
                    self._events.commit()
        finally:
            if ticket is not None:
                ticket.release()
        return result

    def resolve(self, key: K.Pointer, value: Any) -> None:
        future = self.futures.get(key)
        if future is not None and not future.done():
            loop = future.get_loop()
            loop.call_soon_threadsafe(
                lambda: None if future.done() else future.set_result(value)
            )

    def stop(self) -> None:
        self._closed.set()


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    methods: tuple[str, ...] = ("POST",),
    schema: sch.SchemaMetaclass | None = None,
    autocommit_duration_ms: int | None = 50,
    keep_queries: bool | None = None,
    delete_completed_queries: bool = False,
    request_validator: Callable | None = None,
    documentation: Any = None,
    admission: Any = None,
    tenant_field: str = "tenant",
) -> tuple[Table, Callable[[Table], None]]:
    """Expose an HTTP endpoint as an input table; returns the table and a
    ``response_writer(responses)`` that resolves each request's HTTP response
    from the row in ``responses`` with the same key (column ``result``).

    ``admission`` (optional) is an admission controller (see
    ``pathway_tpu/serving/admission.py``): each request is admitted
    against the tenant named by ``payload[tenant_field]`` before its row
    enters the engine, and a shed request gets HTTP 429 + ``Retry-After``
    instead of unbounded buffering."""
    if schema is None:
        schema = sch.schema_from_types(query=str)
    if webserver is None:
        webserver = PathwayWebserver(host or "0.0.0.0", port or 8080)
    subject = RestServerSubject(
        webserver,
        route,
        methods,
        schema,
        delete_completed_queries,
        request_validator,
        admission=admission,
        tenant_field=tenant_field,
    )
    table = input_table(subject, schema, name=f"rest:{route}")

    def response_writer(responses: Table) -> None:
        result_col = "result" if "result" in responses._column_names else responses._column_names[-1]

        def on_change(key: K.Pointer, row: dict, time: int, is_addition: bool) -> None:
            if not is_addition:
                return
            subject.resolve(key, fmt_value(row[result_col]))

        subscribe(responses, on_change=on_change, name="rest_response")

    return table, response_writer
