"""``pw.io.deltalake`` — Delta Lake connector.

Reference: ``python/pathway/io/deltalake`` over the Rust reader
(``src/connectors/data_storage.rs:1924``) and writer (``:1621``), which
use the ``deltalake`` crate.  Re-design: Delta Lake is an open on-disk
format — parquet data files plus a ``_delta_log/NNNNNNNNNNNNNNNNNNNN.json``
commit log — so this build implements the protocol directly on pyarrow
(available offline), no ``deltalake`` package or service needed:

- **writer**: each flushed batch becomes one parquet file and one commit
  holding an ``add`` action (append mode, like the reference's default);
  rows carry the engine's ``time``/``diff`` columns so a Delta table is
  a faithful change stream.
- **reader**: replays the commit log's ``add`` actions in version order;
  streaming mode polls the log for new commits (the same tail-the-log
  discipline the reference reader uses).

Interop: tables written here are readable by any Delta client
(min protocol reader version 1), and tables produced by standard Delta
writers (append-only, no deletion vectors) are readable here.
"""

from __future__ import annotations

import json
import os
import time as _time
import uuid
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import keys_for_values
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import (
    RowSource,
    Writer,
    attach_writer,
    coerce_row,
    format_change_row,
    input_table,
)

__all__ = ["read", "write"]

_LOG_DIR = "_delta_log"


def _log_path(table_path: str, version: int) -> str:
    return os.path.join(table_path, _LOG_DIR, f"{version:020d}.json")


def _list_versions(table_path: str) -> list[int]:
    log = os.path.join(table_path, _LOG_DIR)
    if not os.path.isdir(log):
        return []
    out = []
    for f in os.listdir(log):
        if f.endswith(".json"):
            try:
                out.append(int(f[: -len(".json")]))
            except ValueError:
                continue
    return sorted(out)


def _write_commit(path: str, actions: list[dict]) -> None:
    """Atomic commit publication (tmp + rename): a concurrent reader
    polling the log must never observe an empty or half-written file."""
    tmp = f"{path}.tmp.{uuid.uuid4()}"
    with open(tmp, "w") as f:
        f.write("\n".join(json.dumps(a) for a in actions))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _delta_type(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    if isinstance(v, bytes):
        return "binary"
    return "string"


def _delta_type_of_dtype(d: Any) -> str:
    from pathway_tpu.internals import dtype as dt

    base = d.strip_optional()
    if base == dt.BOOL:
        return "boolean"
    if base == dt.INT:
        return "long"
    if base == dt.FLOAT:
        return "double"
    if base == dt.BYTES:
        return "binary"
    return "string"


class _DeltaWriter(Writer):
    """Append-mode Delta writer: one parquet file + one commit per flush."""

    def __init__(self, table_path: str, dtypes: dict | None = None):
        self.table_path = table_path
        #: engine column dtypes: schemaString must come from the TABLE's
        #: types, not from the first row's values (a leading None would
        #: mistype its column as "string" and break foreign readers)
        self.dtypes = dtypes
        self._rows: list[dict] = []
        self._version: int | None = None

    def _ensure_table(self, sample_row: dict) -> int:
        os.makedirs(os.path.join(self.table_path, _LOG_DIR), exist_ok=True)
        versions = _list_versions(self.table_path)
        if versions:
            return versions[-1] + 1
        # version 0: protocol + metaData actions
        fields = []
        for k, v in sample_row.items():
            if self.dtypes is not None and k in self.dtypes:
                typ = _delta_type_of_dtype(self.dtypes[k])
            elif k in ("time", "diff"):
                typ = "long"
            else:
                typ = _delta_type(v)
            fields.append(
                {"name": k, "type": typ, "nullable": True, "metadata": {}}
            )
        actions = [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": json.dumps(
                        {"type": "struct", "fields": fields}
                    ),
                    "partitionColumns": [],
                    "configuration": {},
                }
            },
        ]
        _write_commit(_log_path(self.table_path, 0), actions)
        return 1

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        self._rows.append(format_change_row(row, time, diff))

    def flush(self) -> None:
        if not self._rows:
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        if self._version is None:
            self._version = self._ensure_table(self._rows[0])
        cols = list(self._rows[0].keys())
        tbl = pa.table({c: [r.get(c) for r in self._rows] for c in cols})
        fname = f"part-{self._version:05d}-{uuid.uuid4()}.snappy.parquet"
        fpath = os.path.join(self.table_path, fname)
        pq.write_table(tbl, fpath)
        add = {
            "add": {
                "path": fname,
                "size": os.path.getsize(fpath),
                "partitionValues": {},
                "modificationTime": int(_time.time() * 1000),
                "dataChange": True,
            }
        }
        _write_commit(_log_path(self.table_path, self._version), [add])
        self._version += 1
        self._rows = []


class _DeltaSource(RowSource):
    """Replays the commit log's ``add`` actions in version order; in
    streaming mode keeps polling for new commits."""

    deterministic_replay = True

    # disjoint key-hash row share per worker, emitted in commit-version
    # order on each rank: same key always lands on the same rank, so
    # per-key arrival order survives the split
    partitioning = "key"
    order_preserving = True

    def __init__(
        self,
        table_path: str,
        schema: sch.SchemaMetaclass,
        *,
        mode: str = "streaming",
        poll_interval: float = 0.5,
        tag: str = "deltalake",
    ):
        self.table_path = table_path
        self.schema = schema
        self.mode = mode
        self.poll_interval = poll_interval
        self.tag = tag
        self._part = (0, 1)

    def partition(self, worker: int, n_workers: int) -> "_DeltaSource":
        import copy

        sub = copy.copy(self)
        sub._part = (worker, n_workers)
        return sub

    def _emit_version(self, events: Any, version: int) -> bool:
        """Emit one commit's added files; True if it produced data."""
        import pyarrow.parquet as pq

        pk = self.schema.primary_key_columns()
        w, n = self._part
        emitted = False
        with open(_log_path(self.table_path, version)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                add = json.loads(line).get("add")
                if add is None:
                    continue
                tbl = pq.read_table(os.path.join(self.table_path, add["path"]))
                has_diff = "diff" in tbl.column_names
                rows = tbl.to_pylist()
                cols = self.schema.column_names()
                if pk:
                    key_args = [tuple(r.get(c) for c in pk) for r in rows]
                else:
                    # content-derived keys: a +1 and its later -1 live in
                    # DIFFERENT commits/files, so positional keys would
                    # never cancel — the change stream must key by value
                    key_args = [
                        ("__delta__", *(r.get(c) for c in cols)) for r in rows
                    ]
                keys = keys_for_values(key_args)
                for r, key in zip(rows, keys):
                    if n > 1 and int(key) % n != w:
                        continue
                    diff = r.get("diff", 1) if has_diff else 1
                    vals = coerce_row(r, self.schema)
                    if diff >= 0:
                        events.add(key, vals)
                    else:
                        events.remove(key, vals)
                    emitted = True
        return emitted

    def run(self, events: Any) -> None:
        done = -1
        while True:
            emitted = False
            for v in _list_versions(self.table_path):
                if v <= done:
                    continue
                try:
                    if self._emit_version(events, v):
                        emitted = True
                except (json.JSONDecodeError, FileNotFoundError, OSError):
                    # a foreign writer publishing non-atomically: do NOT
                    # advance past the torn commit — retry next poll
                    # (static mode consumes what exists and returns)
                    break
                done = v
            if emitted:
                events.commit()
            if self.mode == "static":
                return
            if events.stopped:
                return
            _time.sleep(self.poll_interval)


def read(
    uri: str | os.PathLike,
    *,
    schema: sch.SchemaMetaclass,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str = "deltalake",
    **kwargs: Any,
) -> Table:
    """Read a Delta table (reference ``pw.io.deltalake.read``).  Rows
    written by this module's :func:`write` carry ``diff`` and replay as
    the original change stream; foreign append-only tables read as
    insertions."""
    src = _DeltaSource(os.fspath(uri), schema, mode=mode)
    return input_table(src, schema, name=name)


def write(
    table: Table,
    uri: str | os.PathLike,
    *,
    name: str = "deltalake_out",
    **kwargs: Any,
) -> None:
    """Append the table's change stream to a Delta table (reference
    ``pw.io.deltalake.write``)."""
    attach_writer(
        table, _DeltaWriter(os.fspath(uri), dict(table._dtypes)), name=name
    )
