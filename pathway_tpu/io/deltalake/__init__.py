"""``pw.io.deltalake`` — Delta Lake connector (reference python/pathway/io/deltalake; reader src/connectors/data_storage.rs:1924, writer :1621).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

read = gated_reader("deltalake", "deltalake")
write = gated_writer("deltalake", "deltalake")

__all__ = ["read", "write"]
