"""``pw.io.plaintext`` — read files line-by-line into a ``data: str`` column
(reference ``python/pathway/io/plaintext``)."""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io import fs as _fs

__all__ = ["read"]


def read(path: str | os.PathLike, *, mode: str = "streaming", **kwargs: Any) -> Table:
    return _fs.read(path, format="plaintext", mode=mode, **kwargs)
