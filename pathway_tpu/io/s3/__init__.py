"""``pw.io.s3`` — object-store (S3/MinIO-compatible) connector.

Reference: ``python/pathway/io/s3`` + the Rust S3 scanner with a rayon
download pool (``src/connectors/scanner/s3.rs``).  Re-designed for this
engine: a polling object scanner (list → diff by etag/size → parallel
fetch via a thread pool → deterministic key-ordered emission) feeding the
same line parsers the filesystem connector uses.

The client is boto3-compatible (``list_objects_v2`` / ``get_object``) and
injectable: pass ``AwsS3Settings(client=...)`` for any object store or a
test double; without an injected client, boto3 is imported lazily (absent
in this environment — the API activates when it is installed).
"""

from __future__ import annotations

import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, coerce_row, input_table
from pathway_tpu.io._gated import MissingDependency

__all__ = ["AwsS3Settings", "read"]


class AwsS3Settings:
    """Connection settings (reference ``pw.io.s3.AwsS3Settings``)."""

    def __init__(
        self,
        *,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        with_path_style: bool = False,
        region: str | None = None,
        endpoint: str | None = None,
        client: Any = None,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region
        self.endpoint = endpoint
        self._client = client

    def create_client(self) -> Any:
        if self._client is not None:
            return self._client
        try:
            import boto3  # type: ignore[import-not-found]
        except ImportError as e:
            raise MissingDependency(
                "boto3 is not installed; pass AwsS3Settings(client=...) with "
                "a boto3-compatible client (list_objects_v2/get_object)"
            ) from e
        return boto3.client(
            "s3",
            aws_access_key_id=self.access_key,
            aws_secret_access_key=self.secret_access_key,
            region_name=self.region,
            endpoint_url=self.endpoint,
        )


class _S3Source(RowSource):
    """Scans a bucket prefix; streaming mode re-lists and emits new or
    changed objects (etag/size diff) — the reference's posix-like dir
    watching applied to an object store."""

    deterministic_replay = True

    # disjoint key-hash row share per worker: same key always lands on
    # the same rank, and that rank reads objects in key-sorted order, so
    # per-key arrival order survives the split
    partitioning = "key"
    order_preserving = True

    def __init__(
        self,
        settings: AwsS3Settings,
        prefix: str,
        schema: sch.SchemaMetaclass,
        parser_factory: Callable[[str], Callable[[str], dict | None]],
        *,
        mode: str = "streaming",
        with_metadata: bool = False,
        poll_interval: float = 1.0,
        downloader_threads: int = 8,
        tag: str = "s3",
        object_cache: Any = None,
    ):
        #: optional pathway_tpu.persistence.CachedObjectStorage — serves
        #: unchanged object versions (by ETag) without re-downloading
        self.object_cache = object_cache
        self.settings = settings
        self.prefix = prefix
        self.schema = schema
        self.parser_factory = parser_factory
        self.mode = mode
        self.with_metadata = with_metadata
        self.poll_interval = poll_interval
        self.downloader_threads = downloader_threads
        self.tag = tag
        self._part = (0, 1)

    def partition(self, worker: int, n_workers: int) -> "_S3Source":
        """Every worker lists; each emits a disjoint key-hash row share
        (parallel partitioned reads, reference dataflow.rs:3291)."""
        import copy

        sub = copy.copy(self)
        sub._part = (worker, n_workers)
        return sub

    # ------------------------------------------------------------------
    def _list(self, client: Any) -> list[dict]:
        """All objects under the prefix, key-sorted (paginated)."""
        bucket = self.settings.bucket_name
        out: list[dict] = []
        token: str | None = None
        while True:
            kwargs: dict[str, Any] = {"Bucket": bucket, "Prefix": self.prefix}
            if token:
                kwargs["ContinuationToken"] = token
            resp = client.list_objects_v2(**kwargs)
            out.extend(resp.get("Contents", []))
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        return sorted(out, key=lambda o: o["Key"])

    def _fetch(self, client: Any, key: str, etag: str = "") -> bytes:
        cache = self.object_cache
        uri = f"s3://{self.settings.bucket_name}/{key}"
        if cache is not None and etag:
            hit = cache.get(uri, etag)
            if hit is not None:
                return hit
        body = client.get_object(Bucket=self.settings.bucket_name, Key=key)["Body"]
        data = body.read() if hasattr(body, "read") else bytes(body)
        if cache is not None and etag:
            cache.put(uri, etag, data)
        return data

    def _emit_object(
        self, events: Any, key: str, data: bytes, meta: dict
    ) -> set:
        """Emit an object's rows (upsert adds); returns the emitted row
        KEYS so a later version can delete rows that vanished.  Only keys
        are retained — the downstream upsert input session holds the old
        values, so the reader never duplicates the dataset in memory."""
        pk = self.schema.primary_key_columns()
        parser = self.parser_factory(key)
        w, n = self._part
        seq = 0
        emitted: set = set()
        for raw in data.split(b"\n"):
            line = raw.decode(errors="replace")
            if not line.strip():
                continue
            try:
                values = parser(line + "\n")
            except Exception:
                values = None
            if not isinstance(values, dict):
                continue
            if self.with_metadata:
                values["_metadata"] = meta
            if pk:
                row_key = ref_scalar(*[values[c] for c in pk])
            else:
                seq += 1
                row_key = ref_scalar("__s3__", self.tag, key, seq)
            if n > 1 and int(row_key) % n != w:
                continue
            events.add(row_key, coerce_row(values, self.schema))
            emitted.add(row_key)
        return emitted

    def run(self, events: Any) -> None:
        client = self.settings.create_client()
        seen: dict[str, tuple] = {}  # object key -> (etag, size)
        emitted: dict[str, set] = {}  # object key -> emitted row keys
        while True:
            objects = self._list(client)
            fresh = [
                o
                for o in objects
                if seen.get(o["Key"]) != (o.get("ETag"), o.get("Size"))
            ]
            if fresh:
                # parallel fetch (reference rayon pool, scanner/s3.rs:9-10)
                # with deterministic key-ordered emission
                with ThreadPoolExecutor(self.downloader_threads) as pool:
                    blobs = list(
                        pool.map(
                            lambda o: self._fetch(
                                client, o["Key"], str(o.get("ETag", ""))
                            ),
                            fresh,
                        )
                    )
                for obj, data in zip(fresh, blobs):
                    meta = {
                        "path": f"s3://{self.settings.bucket_name}/{obj['Key']}",
                        "modified_at": str(obj.get("LastModified", "")),
                        "size": obj.get("Size"),
                    }
                    # an object VERSION replaces its predecessor via the
                    # upsert input session: re-added keys overwrite in
                    # place (no-op when unchanged); keys of rows that
                    # VANISHED in the new version are deleted by key
                    # (reference retracts modified objects)
                    new_keys = self._emit_object(events, obj["Key"], data, meta)
                    for row_key in emitted.get(obj["Key"], set()) - new_keys:
                        events.remove(row_key, ())
                    emitted[obj["Key"]] = new_keys
                    seen[obj["Key"]] = (obj.get("ETag"), obj.get("Size"))
                events.commit()
            if self.mode == "static":
                return
            if events.stopped:
                return
            _time.sleep(self.poll_interval)


def _parser_for(
    format: str, schema: sch.SchemaMetaclass, csv_settings: Any
) -> Callable[[str], Callable[[str], dict | None]]:
    if format in ("plaintext", "binary"):
        return lambda _key: (lambda line: {"data": line.rstrip("\n")})
    if format in ("json", "jsonlines"):
        import json

        def factory(_key: str):
            def parse(line: str):
                obj = json.loads(line)
                return obj if isinstance(obj, dict) else None

            return parse

        return factory
    if format == "csv":
        import csv as _csv
        import io as _io

        from pathway_tpu.io.csv import CsvParserSettings

        settings = csv_settings or CsvParserSettings()

        def factory(_key: str):
            state: dict[str, Any] = {"header": None}

            def parse(line: str) -> dict | None:
                line = line.rstrip("\n").rstrip("\r")
                if not line:
                    return None
                row = next(_csv.reader(_io.StringIO(line), **settings.reader_kwargs()))
                if state["header"] is None:
                    state["header"] = row
                    return None
                return dict(zip(state["header"], row))

            return parse

        return factory
    raise ValueError(f"unsupported s3 format {format!r}")


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "jsonlines",
    schema: sch.SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    with_metadata: bool = False,
    downloader_threads_count: int = 8,
    name: str = "s3",
    object_cache: Any = None,
    **kwargs: Any,
) -> Table:
    """Read objects under ``path`` (``s3://bucket/prefix``, or a bare
    prefix with ``aws_s3_settings.bucket_name`` set)."""
    settings = aws_s3_settings or AwsS3Settings()
    prefix = path
    if path.startswith("s3://"):
        rest = path[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        settings.bucket_name = settings.bucket_name or bucket
    if schema is None:
        schema = sch.schema_from_types(data=str)
        if format in ("json", "jsonlines"):
            format = "plaintext"
    src = _S3Source(
        settings,
        prefix,
        schema,
        _parser_for(format, schema, csv_settings),
        mode=mode,
        with_metadata=with_metadata,
        downloader_threads=downloader_threads_count,
        tag=f"s3:{settings.bucket_name}/{prefix}",
        object_cache=object_cache,
    )
    # upsert session: object re-reads overwrite by key (reference
    # SessionType::Upsert for key-overwrite sources)
    return input_table(src, schema, name=name, upsert=True)
