"""``pw.io.s3`` — S3/MinIO object reader (reference
``python/pathway/io/s3``; scanner ``src/connectors/scanner/s3.rs``).

Uses fsspec's s3 backend when available; otherwise raises at call time.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import require


class AwsS3Settings:
    def __init__(self, *, bucket_name: str | None = None, access_key: str | None = None,
                 secret_access_key: str | None = None, region: str | None = None,
                 endpoint: str | None = None, with_path_style: bool = False):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style


def read(path: str, *args: Any, format: str = "json", **kwargs: Any) -> Any:
    require("s3fs")
    raise NotImplementedError(
        "pw.io.s3.read: s3fs present but transport not wired in this build"
    )


__all__ = ["read", "AwsS3Settings"]
