"""``pw.io.pyfilesystem`` — read files from any PyFilesystem source
(reference ``python/pathway/io/pyfilesystem``: one row per file, binary
``data`` column, optional ``_metadata``).

The FS object itself is the injection point (the reference signature
takes an ``fs.base.FS`` too); only the duck-typed subset is used —
``walk.files()`` (or ``listdir``), ``readbytes``/``open``, ``getinfo``
— so tests pass a plain fake and any `fs <https://pypi.org/project/fs/>`_
filesystem (zip/tar/s3/ftp/mem) works when the package is installed.

Upsert semantics: a file whose size/mtime changes re-emits under the
same path key, replacing the previous row; deleted files retract.
"""

from __future__ import annotations

import time as _time
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, input_table

__all__ = ["read"]


def _iter_files(source: Any, path: str) -> list[str]:
    walk = getattr(source, "walk", None)
    if walk is not None and hasattr(walk, "files"):
        return sorted(walk.files(path=path or "/"))
    # minimal fallback: non-recursive listdir
    base = (path or "/").rstrip("/")
    return sorted(
        f"{base}/{name}" for name in source.listdir(path or "/")
    )


def _read_bytes(source: Any, path: str) -> bytes:
    rb = getattr(source, "readbytes", None)
    if rb is not None:
        return rb(path)
    with source.open(path, "rb") as f:
        return f.read()


def _version(source: Any, path: str) -> Any:
    getinfo = getattr(source, "getinfo", None)
    if getinfo is None:
        return None
    try:
        info = getinfo(path, namespaces=["details"])
    except TypeError:
        info = getinfo(path)
    size = getattr(info, "size", None)
    modified = getattr(info, "modified", None)
    return (size, str(modified))


class _PyFsSource(RowSource):
    deterministic_replay = True

    def __init__(
        self,
        source: Any,
        path: str,
        schema: sch.SchemaMetaclass,
        *,
        refresh_interval: float = 30,
        mode: str = "streaming",
        with_metadata: bool = False,
    ):
        self.source = source
        self.path = path
        self.schema = schema
        self.refresh_interval = refresh_interval
        self.mode = mode
        self.with_metadata = with_metadata

    def run(self, events: Any) -> None:
        seen: dict[str, Any] = {}
        while True:
            emitted = False
            current = set()
            for fp in _iter_files(self.source, self.path):
                current.add(fp)
                ver = _version(self.source, fp)
                if fp in seen and (ver is None or seen[fp] == ver):
                    # unchanged (or unversionable: emit once only) —
                    # decided BEFORE the download, so polls are free
                    continue
                data = _read_bytes(self.source, fp)
                row: tuple = (data,)
                if self.with_metadata:
                    row = (data, {"path": fp, "version": str(ver)})
                events.add(ref_scalar("__pyfs__", fp), row)
                seen[fp] = ver
                emitted = True
            for fp in list(seen):
                if fp not in current:
                    del seen[fp]
                    events.remove(ref_scalar("__pyfs__", fp), (b"",))
                    emitted = True
            if emitted:
                events.commit()
            if self.mode == "static":
                return
            if events.stopped:
                return
            _time.sleep(self.refresh_interval)


def read(
    source: Any,
    *,
    path: str = "",
    refresh_interval: float = 30,
    mode: str = "streaming",
    with_metadata: bool = False,
    name: str = "pyfilesystem",
    **kwargs: Any,
) -> Table:
    """One row per file under ``path`` of the PyFilesystem ``source``."""
    if with_metadata:
        schema = sch.schema_from_types(data=bytes, _metadata=dict)
    else:
        schema = sch.schema_from_types(data=bytes)
    src = _PyFsSource(
        source,
        path,
        schema,
        refresh_interval=refresh_interval,
        mode=mode,
        with_metadata=with_metadata,
    )
    return input_table(src, schema, name=name, upsert=True)
