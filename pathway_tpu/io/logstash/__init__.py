"""``pw.io.logstash`` — Logstash HTTP-input sink (reference
``python/pathway/io/logstash``): every update is POSTed as a flat JSON
object with extra ``time``/``diff`` fields.

The sender is injectable (``sender(endpoint, payload_bytes)``); the
default uses urllib with the configured retry count.
"""

from __future__ import annotations

import json
import time as _time
from typing import Any, Callable

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer, format_change_row

__all__ = ["write"]


def _default_sender(endpoint: str, payload: bytes) -> None:
    import urllib.request

    req = urllib.request.Request(
        endpoint,
        data=payload,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    urllib.request.urlopen(req, timeout=10).read()


class _LogstashWriter(Writer):
    def __init__(self, endpoint: str, n_retries: int, sender: Callable):
        self.endpoint = endpoint
        self.n_retries = n_retries
        self.sender = sender

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        payload = json.dumps(format_change_row(row, time, diff)).encode()
        attempt = 0
        while True:
            try:
                self.sender(self.endpoint, payload)
                return
            except Exception:
                attempt += 1
                if attempt > self.n_retries:
                    raise
                _time.sleep(min(0.1 * 2**attempt, 2.0))


def write(
    table: Table,
    endpoint: str,
    n_retries: int = 0,
    retry_policy: Any = None,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int | None = None,
    *,
    sender: Callable | None = None,
) -> None:
    """Send the table's update stream to a Logstash HTTP input."""
    attach_writer(
        table,
        _LogstashWriter(endpoint, n_retries, sender or _default_sender),
        name="logstash_out",
    )
