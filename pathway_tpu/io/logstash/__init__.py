"""``pw.io.logstash`` — Logstash sink (reference python/pathway/io/logstash).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

write = gated_writer("logstash", "aiohttp")

__all__ = ["write"]
