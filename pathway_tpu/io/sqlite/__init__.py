"""``pw.io.sqlite`` — SQLite connector (reference ``python/pathway/io/sqlite``;
engine reader ``src/connectors/data_storage.rs:1415``).

Static snapshot read plus polling CDC in streaming mode: the table is
re-scanned when ``PRAGMA data_version`` changes, and row-level adds/removes
are emitted as diffs keyed by primary key.
"""

from __future__ import annotations

import sqlite3
import time as _time
from typing import Any

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import RowSource, coerce_row, input_table

__all__ = ["read"]


class _SqliteSource(RowSource):
    def __init__(self, path: str, table_name: str, schema: sch.SchemaMetaclass, mode: str, poll_interval: float = 0.25):
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.mode = mode
        self.poll_interval = poll_interval

    def _snapshot(self, conn: sqlite3.Connection) -> dict:
        cols = self.schema.column_names()
        cur = conn.execute(
            f"SELECT {', '.join(cols)} FROM {self.table_name}"  # noqa: S608
        )
        pk = self.schema.primary_key_columns()
        out = {}
        for i, row in enumerate(cur.fetchall()):
            values = dict(zip(cols, row))
            if pk:
                key = ref_scalar(*[values[c] for c in pk])
            else:
                key = ref_scalar("__sqlite__", self.table_name, i)
            out[key] = coerce_row(values, self.schema)
        return out

    def run(self, events: Any) -> None:
        conn = sqlite3.connect(self.path)
        try:
            current = self._snapshot(conn)
            for key, row in current.items():
                events.add(key, row)
            events.commit()
            if self.mode == "static":
                return
            last_version = conn.execute("PRAGMA data_version").fetchone()[0]
            while not events.stopped:
                _time.sleep(self.poll_interval)
                version = conn.execute("PRAGMA data_version").fetchone()[0]
                if version == last_version:
                    continue
                last_version = version
                new = self._snapshot(conn)
                changed = False
                for key in set(current) - set(new):
                    events.remove(key, current[key])
                    changed = True
                for key, row in new.items():
                    if key not in current:
                        events.add(key, row)
                        changed = True
                    elif current[key] != row:
                        events.remove(key, current[key])
                        events.add(key, row)
                        changed = True
                current = new
                if changed:
                    events.commit()
        finally:
            conn.close()


def read(
    path: str,
    table_name: str,
    schema: sch.SchemaMetaclass,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str = "sqlite",
    **kwargs: Any,
) -> Table:
    src = _SqliteSource(path, table_name, schema, mode)
    return input_table(src, schema, name=name, upsert=True)
