"""``pw.io.mongodb`` — MongoDB sink (reference ``python/pathway/io/mongodb``;
Rust writer ``src/connectors/data_storage.rs:2232``).

Each epoch's updates flush as one ``insert_many`` of BSON-able documents
carrying the engine's ``time``/``diff`` fields (the reference writes the
change stream the same way — a modification is a -1 doc then a +1 doc).
The client is injectable (anything shaped like ``pymongo.MongoClient``:
``client[db][collection].insert_many(docs)``); without one, pymongo is
imported lazily.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer, format_change_row
from pathway_tpu.io._gated import MissingDependency

__all__ = ["write"]


class _MongoWriter(Writer):
    def __init__(
        self,
        connection_string: str,
        database: str,
        collection: str,
        max_batch_size: int | None,
        client: Any,
    ):
        self.connection_string = connection_string
        self.database = database
        self.collection = collection
        self.max_batch_size = max_batch_size
        self._client = client
        self._docs: list[dict] = []

    def _coll(self) -> Any:
        if self._client is None:
            try:
                from pymongo import MongoClient  # type: ignore[import-not-found]
            except ImportError as e:
                raise MissingDependency(
                    "pymongo is not installed; pass client= with a "
                    "MongoClient-compatible object"
                ) from e
            self._client = MongoClient(self.connection_string)
        return self._client[self.database][self.collection]

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        self._docs.append(format_change_row(row, time, diff))
        if self.max_batch_size and len(self._docs) >= self.max_batch_size:
            self.flush()

    def flush(self) -> None:
        if self._docs:
            self._coll().insert_many(self._docs)
            self._docs = []


def write(
    table: Table,
    *,
    connection_string: str,
    database: str,
    collection: str,
    max_batch_size: int | None = None,
    client: Any = None,
    name: str = "mongodb_out",
) -> None:
    """Write the table's change stream to a MongoDB collection."""
    attach_writer(
        table,
        _MongoWriter(connection_string, database, collection, max_batch_size, client),
        name=name,
    )
