"""``pw.io.slack`` — Slack alert sink (reference
``python/pathway/io/slack``: ``send_alerts(alerts, channel, token)``).

Every ADDED value of the alert column becomes one
``chat.postMessage`` call (retractions are ignored — an alert, once
sent, cannot be unsent).  The HTTP poster is injectable
(``poster(url, headers, payload_dict)``); the default uses urllib —
no slack_sdk dependency.
"""

from __future__ import annotations

import json
from typing import Any, Callable

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.io._connector import Writer, attach_writer

__all__ = ["send_alerts"]

_API_URL = "https://slack.com/api/chat.postMessage"


def _default_poster(url: str, headers: dict, payload: dict) -> None:
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=headers, method="POST"
    )
    urllib.request.urlopen(req, timeout=10).read()


class _SlackWriter(Writer):
    def __init__(self, channel: str, token: str, column: str, poster: Callable):
        self.channel = channel
        self.token = token
        self.column = column
        self.poster = poster

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        if diff <= 0:
            return  # alerts are not retractable
        self.poster(
            _API_URL,
            {
                "Content-Type": "application/json",
                "Authorization": f"Bearer {self.token}",
            },
            {"channel": self.channel, "text": str(row[self.column])},
        )


def send_alerts(
    alerts: ColumnReference,
    slack_channel_id: str,
    slack_token: str,
    *,
    poster: Callable | None = None,
) -> None:
    """Post every new value of ``alerts`` to a Slack channel."""
    table = alerts._table.select(alert=alerts)
    attach_writer(
        table,
        _SlackWriter(
            slack_channel_id, slack_token, "alert", poster or _default_poster
        ),
        name="slack_out",
    )


write = send_alerts  # convenience alias
