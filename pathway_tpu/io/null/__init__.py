"""``pw.io.null`` — sink that drops everything (reference NullWriter,
``src/connectors/data_storage.rs:1395``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer

__all__ = ["write"]


class _NullWriter(Writer):
    def write(self, row: dict, time: int, diff: int) -> None:
        pass


def write(table: Table, **kwargs: Any) -> None:
    attach_writer(table, _NullWriter(), name="null")
