"""``pw.io.airbyte`` — Airbyte-protocol sources (reference
``python/pathway/io/airbyte`` + vendored ``airbyte_serverless``).

TPU-build redesign: the reference launches the connector as a Docker
image or a PyPI package in a venv and speaks the `Airbyte protocol
<https://docs.airbyte.com/understanding-airbyte/airbyte-protocol>`_ over
its stdout.  This environment has no Docker daemon and no egress, so the
execution layer here runs any LOCAL executable speaking that same
protocol (``spec``/``discover``/``read`` subcommands emitting JSONL
``AirbyteMessage``\\s) — which is exactly what a connector container
does inside — while the Docker/PyPI launch paths stay gated with the
original error.  Everything above the execution layer is full fidelity:

- catalog discovery and per-stream sync-mode selection (``incremental``
  preferred, ``full_refresh`` fallback — reference ``logic.py:15-16``);
- the incremental STATE machinery: ``LEGACY`` / ``GLOBAL`` / ``STREAM``
  state messages folded into one global envelope that is handed back to
  the connector on the next poll (reference
  ``logic.py:_PathwayAirbyteDestination``);
- commit boundaries at STATE messages, so each poll's rows become
  engine transactions aligned with the connector's own checkpoints;
- ``full_refresh`` snapshot diffing: unchanged rows don't churn,
  disappeared rows are retracted (reference ``logic.py:on_event``);
- durable state (``state_path``): the state envelope is written at every
  commit, so a restarted pipeline resumes the incremental sync instead
  of re-extracting history.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import tempfile
import time
from typing import Any, Sequence

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import coerce_row
from pathway_tpu.io.python import ConnectorSubject
from pathway_tpu.io.python import read as python_read

__all__ = ["read", "ExecutableAirbyteSource", "AirbyteStateTracker"]

_logger = logging.getLogger("pathway_tpu")

MAX_RETRIES = 5
INCREMENTAL_SYNC_MODE = "incremental"
FULL_REFRESH_SYNC_MODE = "full_refresh"


class AirbyteStateTracker:
    """Folds Airbyte STATE messages into one resumable global envelope.

    The protocol has three state flavors (the reference handles the same
    trio, ``logic.py:68-131``): ``LEGACY`` (one opaque blob), ``STREAM``
    (per-stream descriptors), and ``GLOBAL`` (stream states + an
    optional shared state).  The tracker accepts any mix and renders a
    ``GLOBAL`` envelope — the most general form — to feed back to the
    connector's ``--state``.
    """

    def __init__(self) -> None:
        self._stream_states: dict[str, Any] = {}
        self._shared_state: Any = None
        self._legacy: Any = None

    def observe(self, state_msg: dict) -> None:
        """Fold one STATE message payload (the ``state`` field)."""
        state_type = state_msg.get("type", "LEGACY")
        if state_type == "LEGACY":
            blob = state_msg.get("data")
            if blob is None:
                _logger.warning("airbyte LEGACY state without 'data'")
            else:
                self._legacy = blob
            return
        if state_type in ("STREAM", "PER_STREAM"):
            self._fold_stream(state_msg.get("stream"))
            return
        if state_type == "GLOBAL":
            g = state_msg.get("global")
            if g is None:
                _logger.warning("airbyte GLOBAL state without 'global'")
                return
            for s in g.get("stream_states") or []:
                self._fold_stream(s)
            self._shared_state = g.get("shared_state")
            return
        _logger.warning("unknown airbyte state type %r ignored", state_type)

    def _fold_stream(self, stream: Any) -> None:
        if not isinstance(stream, dict):
            _logger.warning("airbyte stream state without 'stream' section")
            return
        desc = stream.get("stream_descriptor") or {}
        name = desc.get("name")
        if name is None:
            _logger.warning("airbyte stream state without descriptor name")
            return
        self._stream_states[name] = stream.get("stream_state")

    def envelope(self) -> dict | None:
        """The state to hand back to the connector (None = from scratch)."""
        if self._stream_states or self._shared_state is not None:
            g: dict[str, Any] = {
                "stream_states": [
                    {
                        "stream_descriptor": {"name": name},
                        "stream_state": state,
                    }
                    for name, state in self._stream_states.items()
                ]
            }
            if self._shared_state is not None:
                g["shared_state"] = self._shared_state
            return {"type": "GLOBAL", "global": g}
        if self._legacy is not None:
            return {"type": "LEGACY", "data": self._legacy}
        return None

    def load(self, envelope: dict | None) -> None:
        self._stream_states = {}
        self._shared_state = None
        self._legacy = None
        if envelope:
            self.observe(envelope)


class ExecutableAirbyteSource:
    """Runs a local Airbyte-protocol executable.

    ``command`` is the argv prefix (e.g. ``["python", "my_source.py"]``
    or a connector binary); the source invokes ``<command> discover
    --config f`` once and ``<command> read --config f --catalog f
    [--state f]`` per poll, parsing JSONL ``AirbyteMessage``\\s from
    stdout.  This is the role of the reference's Docker/venv runners
    with the container layer stripped away.
    """

    def __init__(
        self,
        command: Sequence[str],
        *,
        config: dict | None = None,
        streams: Sequence[str] | None = None,
        catalog: dict | None = None,
        env_vars: dict[str, str] | None = None,
    ):
        self.command = list(command)
        self.config = config or {}
        self.streams = list(streams or [])
        self._catalog = catalog
        self._configured: dict | None = None
        self.env_vars = env_vars

    # -- protocol plumbing ---------------------------------------------
    def _run(self, args: list[str], *, timeout: float = 600.0) -> list[dict]:
        env = dict(os.environ, **(self.env_vars or {}))
        proc = subprocess.run(
            self.command + args,
            capture_output=True,
            timeout=timeout,
            env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"airbyte connector {self.command} failed: "
                f"{proc.stderr.decode(errors='replace')[-1000:]}"
            )
        out = []
        for line in proc.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                _logger.debug("non-JSON connector output: %r", line[:200])
        return out

    def _tmp_json(self, d: str, name: str, payload: Any) -> str:
        path = os.path.join(d, name)
        with open(path, "w") as f:
            json.dump(payload, f)
        return path

    def discover(self) -> dict:
        """The connector's catalog (cached)."""
        if self._catalog is not None:
            return self._catalog
        with tempfile.TemporaryDirectory(prefix="pw_airbyte_") as d:
            cfg = self._tmp_json(d, "config.json", self.config)
            messages = self._run(["discover", "--config", cfg])
        for m in messages:
            if m.get("type") == "CATALOG":
                self._catalog = m["catalog"]
                return self._catalog
        raise RuntimeError("airbyte connector emitted no CATALOG message")

    @property
    def configured_catalog(self) -> dict:
        """Configured catalog over the requested streams; incremental
        sync when the stream supports it, full refresh otherwise."""
        if self._configured is not None:
            return self._configured
        catalog = self.discover()
        wanted = set(self.streams) or {
            s["name"] for s in catalog.get("streams", [])
        }
        configured = []
        for s in catalog.get("streams", []):
            if s["name"] not in wanted:
                continue
            modes = s.get("supported_sync_modes") or ["full_refresh"]
            sync = (
                INCREMENTAL_SYNC_MODE
                if INCREMENTAL_SYNC_MODE in modes
                else FULL_REFRESH_SYNC_MODE
            )
            configured.append(
                {
                    "stream": s,
                    "sync_mode": sync,
                    "destination_sync_mode": "append",
                }
            )
        missing = wanted - {c["stream"]["name"] for c in configured}
        if missing:
            raise ValueError(f"streams not found in catalog: {sorted(missing)}")
        self._configured = {"streams": configured}
        return self._configured

    @property
    def sync_mode(self) -> str:
        return self.configured_catalog["streams"][0]["sync_mode"]

    def extract(self, state: dict | None) -> list[dict]:
        """One ``read`` pass; returns RECORD/STATE messages in order."""
        with tempfile.TemporaryDirectory(prefix="pw_airbyte_") as d:
            args = [
                "read",
                "--config",
                self._tmp_json(d, "config.json", self.config),
                "--catalog",
                self._tmp_json(d, "catalog.json", self.configured_catalog),
            ]
            if state is not None:
                args += ["--state", self._tmp_json(d, "state.json", state)]
            messages = self._run(args)
        return [
            m for m in messages if m.get("type") in ("RECORD", "STATE")
        ]

    def on_stop(self) -> None:
        pass


class _AirbyteSubject(ConnectorSubject):
    """Polls the source, emits rows, commits at connector STATE
    checkpoints, and persists the state envelope (reference
    ``logic.py:_PathwayAirbyteSubject``)."""

    def __init__(
        self,
        source: ExecutableAirbyteSource,
        *,
        mode: str,
        refresh_interval_ms: int,
        state_path: str | None = None,
    ):
        super().__init__(datasource_name="airbyte")
        self.source = source
        self.mode = mode
        self.refresh_interval = refresh_interval_ms / 1000.0
        self.state_path = state_path
        self.tracker = AirbyteStateTracker()
        if state_path and os.path.exists(state_path):
            with open(state_path) as f:
                self.tracker.load(json.load(f))
        #: full-refresh snapshot diffing: content-key -> coerced row
        self._cache: dict[K.Pointer, tuple] = {}
        self._present: set[K.Pointer] = set()

    # -- emission -------------------------------------------------------
    def _emit(self, payload: dict) -> None:
        if self.source.sync_mode == INCREMENTAL_SYNC_MODE:
            self.next_json({"data": payload})
            return
        # full refresh: content-addressed upsert; unchanged rows no-op
        message = json.dumps(
            {"data": payload}, ensure_ascii=False, sort_keys=True
        )
        key = K.ref_scalar("__airbyte__", message)
        self._present.add(key)
        if key not in self._cache:
            row = coerce_row({"data": payload}, self._schema)
            self._cache[key] = row
            self._events.add(key, row)

    def _retract_absent(self) -> None:
        absent = [k for k in self._cache if k not in self._present]
        for key in absent:
            self._events.remove(key, self._cache.pop(key))
        self._present.clear()

    def _checkpoint(self) -> None:
        """Commit + durably save the state envelope at a connector
        checkpoint, in that order: the engine log's commit record and
        the saved state then describe the same frontier."""
        self.commit()
        if self.state_path:
            env = self.tracker.envelope()
            tmp = f"{self.state_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(env, f)
            os.replace(tmp, self.state_path)

    # -- main loop ------------------------------------------------------
    def run(self) -> None:
        failures = 0
        while True:
            started = time.monotonic()
            try:
                messages = self.source.extract(self.tracker.envelope())
            except Exception:
                _logger.exception("airbyte extract failed, retrying")
                failures += 1
                if failures >= MAX_RETRIES:
                    raise
                time.sleep(min(1.5**failures, 30.0))
                continue
            failures = 0
            saw_state = False
            for m in messages:
                if m["type"] == "RECORD":
                    self._emit(m["record"]["data"])
                elif m["type"] == "STATE":
                    self.tracker.observe(m["state"])
                    saw_state = True
                    if self.source.sync_mode == INCREMENTAL_SYNC_MODE:
                        self._checkpoint()
            if self.source.sync_mode == FULL_REFRESH_SYNC_MODE:
                self._retract_absent()
            if not saw_state or self.source.sync_mode == FULL_REFRESH_SYNC_MODE:
                self._checkpoint()
            if self.mode == "static":
                return
            if self.stopped:
                return
            # poll cadence; wake early when the run is shutting down
            deadline = started + self.refresh_interval
            while time.monotonic() < deadline:
                if self.stopped:
                    return
                time.sleep(min(0.2, max(0.0, deadline - time.monotonic())))

    def on_stop(self) -> None:
        self.source.on_stop()


def _load_source_config(config: Any) -> dict:
    if isinstance(config, dict):
        return config
    with open(config) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml

            return yaml.safe_load(text)
        except ImportError as e:
            raise ValueError(
                "config file is not JSON and pyyaml is unavailable"
            ) from e


def read(
    config_file_path: Any,
    streams: Sequence[str],
    *,
    execution_type: str = "local",
    mode: str = "streaming",
    env_vars: dict[str, str] | None = None,
    refresh_interval_ms: int = 60000,
    enforce_method: str | None = None,
    state_path: str | None = None,
    command: Sequence[str] | None = None,
    catalog: dict | None = None,
    name: str = "airbyte",
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a table through an Airbyte-protocol connector.

    ``config_file_path`` is a config dict or a JSON/YAML file whose
    ``source`` section holds the connector settings.  The executable is
    taken from ``command`` (argv prefix) or the config's
    ``source.command``; Docker images / PyPI venvs / remote GCP jobs
    (the reference's launchers) need a container runtime / egress that
    this environment lacks and raise the original gating error.  See the
    module docstring for the protocol/state semantics.
    """
    cfg = _load_source_config(config_file_path)
    source_cfg = cfg.get("source", cfg)
    cmd = list(command) if command else source_cfg.get("command")
    if not cmd:
        from pathway_tpu.io._gated import gated_reader

        if execution_type != "local" or source_cfg.get("docker_image"):
            gated_reader("airbyte", "airbyte_serverless", "docker")()
        raise ValueError(
            "airbyte: provide `command=[...]` (a local Airbyte-protocol "
            "executable) or a config with source.command; docker/pypi "
            "launchers need a container runtime unavailable here"
        )
    source = ExecutableAirbyteSource(
        cmd,
        config=source_cfg.get("config"),
        streams=streams,
        catalog=catalog,
        env_vars=env_vars,
    )
    subject = _AirbyteSubject(
        source,
        mode=mode,
        refresh_interval_ms=refresh_interval_ms,
        state_path=state_path,
    )
    schema = sch.schema_from_types(data=dict)
    return python_read(subject, schema=schema, name=name, **kwargs)
