"""``pw.io.airbyte`` — Airbyte-sourced tables (reference
``python/pathway/io/airbyte`` + vendored ``airbyte_serverless``).

Intentionally gated, not implemented: the reference runs an Airbyte
SOURCE CONTAINER (Docker, or a GCP Cloud Run job) and speaks the Airbyte
protocol over its stdout — the connector's substance is container
orchestration plus each source's own OAuth/config flow, none of which
exists in this environment (no Docker daemon, zero egress).  The
incremental-state bookkeeping the wrapper adds on top is already
exercised by this build's Debezium/Kafka upsert paths.  The API surface
matches the reference so code written against it ports; calls raise
``MissingDependency`` until a container runtime + ``airbyte-serverless``
are available.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader

read = gated_reader("airbyte", "airbyte_serverless", "docker")

__all__ = ["read"]
