"""``pw.io.airbyte`` — Airbyte serverless source (reference python/pathway/io/airbyte + vendored airbyte_serverless).

API-surface parity module: the row/format plumbing routes through the shared
connector framework; the transport activates when the client library is
available (external services are unreachable in this build environment).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._gated import gated_reader, gated_writer

read = gated_reader("airbyte", "airbyte_serverless")

__all__ = ["read"]
