"""``pw.io.fs`` — filesystem connector: single files or directories, static
or watched-streaming (reference ``python/pathway/io/fs``; engine POSIX-like
scanner ``src/connectors/posix_like.rs``, ``scanner/filesystem.rs``)."""

from __future__ import annotations

import os
import time as _time
from typing import Any, Callable

from pathway_tpu.engine.columnar import columnar_enabled as _columnar_enabled
from pathway_tpu.internals import native as _native_mod
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import keys_for_values, ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import (
    LazyFileWriter,
    RowSource,
    attach_writer,
    coerce_row,
    coerce_rows,
    fmt_value,
    input_table,
)

__all__ = ["read", "write"]


def _list_files(path: str) -> list[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return sorted(out)
    import glob

    if any(ch in path for ch in "*?["):
        return sorted(glob.glob(path))
    return [path] if os.path.exists(path) else []


def _nonempty_lines_before(f, nbytes: int, block: int) -> int:
    """Count non-empty lines in the first ``nbytes`` of an open binary
    file — the global line-seq base for a byte-range share (row keys hash
    the global sequence number, so a worker starting mid-file must know
    how many lines precede it).  Newline counting is memchr-speed with no
    per-line allocation; only blocks actually containing empty lines pay
    a split."""
    count = 0
    prev_nl = True  # start-of-file behaves like "just after a newline"
    left = nbytes
    while left > 0:
        b = f.read(min(block, left))
        if not b:
            break
        left -= len(b)
        if b"\n\n" not in b and not (prev_nl and b.startswith(b"\n")):
            # no empty line anywhere: every newline ends a non-empty line
            count += b.count(b"\n")
        else:
            parts = b.split(b"\n")
            if len(parts) > 1:
                # parts[0] closes a line opened earlier (non-empty if it
                # has bytes here or had any before this block)
                if parts[0] or not prev_nl:
                    count += 1
                count += sum(1 for p in parts[1:-1] if p)
        prev_nl = b.endswith(b"\n")
    return count


class _FilesSource(RowSource):
    """Reads lines of files under a path; in streaming mode polls for new
    files and appended lines (reference filesystem scanner + dir watching)."""

    deterministic_replay = True

    # multi-worker reads split files by byte range (static, stateless
    # parser) or interleaved line share; either way two rows with the
    # same key can land on different ranks, so cross-rank per-key arrival
    # order is NOT preserved (the PR 9 keyed-upsert gotcha — PW-X001)
    partitioning = "byte-range"
    order_preserving = False

    def __init__(
        self,
        path: str,
        schema: sch.SchemaMetaclass,
        *,
        parse_line: Callable[[str], dict | None] | None = None,
        parser_factory: Callable[[str], Callable[[str], dict | None]] | None = None,
        parse_block: Callable[[bytes], "list[dict] | None"] | None = None,
        frame_plan: tuple | None = None,
        mode: str = "streaming",
        poll_interval: float = 0.2,
        with_metadata: bool = False,
        tag: str = "fs",
    ):
        self.path = path
        self.schema = schema
        #: optional columnar fast path: parse a block of COMPLETE lines at
        #: once (e.g. pandas' C JSON parser); returning None falls back to
        #: the per-line parser for that block (e.g. malformed rows)
        self.parse_block = parse_block
        #: native schema plan for frame_parse_jsonl (set by formats whose
        #: lines are flat JSON objects): a block of lines parses straight
        #: into a columnar frame — typed column arrays + interned string
        #: pool + LAZY row keys — and enters the engine via add_frame
        #: with no per-row Python objects at all.  None = row path.
        self.frame_plan = frame_plan
        # parser_factory(fp) -> line parser with per-file state (CSV headers);
        # plain parse_line is wrapped as a stateless factory.  Stateless
        # parsers allow the pre-parse line partition (each worker parses
        # only its share); stateful ones must see every line (headers), so
        # partitioned workers filter at emit instead
        self._stateless_parser = parser_factory is None
        if parser_factory is None:
            assert parse_line is not None
            parser_factory = lambda fp, p=parse_line: p
        self.parser_factory = parser_factory
        self.mode = mode
        self.poll_interval = poll_interval
        self.with_metadata = with_metadata
        self.tag = tag
        #: (worker, n_workers) — this reader emits only rows whose key
        #: hash it owns (parallel partitioned reads, reference
        #: ``connector_table(parallel_readers=...)`` dataflow.rs:3291)
        self._part = (0, 1)

    def partition(self, worker: int, n_workers: int) -> "_FilesSource | None":
        """Disjoint share per worker: static files with stateless parsers
        split by BYTE RANGE (each worker reads only its 1/n of the file);
        streaming appends fall back to the interleaved line-index share
        (stateful parsers see every line and filter at emit).  Row keys
        are identical to a single-worker run either way, so persistence
        resume and N-vs-1-worker outputs stay exact.  Downstream placement
        is the consumers' business — every routed operator re-exchanges
        its input."""
        import copy

        sub = copy.copy(self)
        sub._part = (worker, n_workers)
        return sub

    def _emit_file(
        self, events: Any, fp: str, start_offset: int, seq_start: int, parser: Callable
    ) -> tuple[int, int]:
        pk = self.schema.primary_key_columns()
        seq = seq_start  # non-empty LINE counter (keys + partitioning)
        add_many = getattr(events, "add_many", None)
        chunk: list = []  # (key, row) additions flushed per _CHUNK rows
        _CHUNK = 16384
        _BLOCK = 8 << 20
        schema = self.schema
        meta = (
            {"path": fp, "modified_at": int(os.path.getmtime(fp))}
            if self.with_metadata
            else None
        )
        w, n = self._part
        # columnar ingest gate, decided once per file: the native JSONL->
        # frame parser replicates coerce_rows + hash_prefix_ints exactly
        # (strict subset — anything unusual returns None and the block
        # falls back to the row path), so it is sound whenever keys are
        # seq-derived (no primary key), no metadata column is spliced in,
        # and the engine accepts frames (events.add_frame)
        _native = _native_mod.load()
        add_frame = getattr(events, "add_frame", None)
        frame_prefix = ("__fs__", self.tag, fp)
        frame_ok = (
            self.frame_plan is not None
            and add_frame is not None
            and _native is not None
            and not pk
            and meta is None
            and _columnar_enabled()
        )
        # static files with stateless parsers partition by BYTE RANGE:
        # the interleaved line share makes every worker read AND split the
        # whole file (the split allocates one object per line), a fixed
        # per-process cost that grows with worker count.  A byte range
        # reads 1/n of the file; the seq base for key stability comes
        # from a newline count over the prefix (no allocation).  Line
        # ownership changes, but keys hash the global line seq, so the
        # union of shares is byte-identical to a single-worker run.
        byte_range = None
        if (
            n > 1
            and start_offset == 0
            and self.mode == "static"
            and self._stateless_parser
        ):
            size = os.path.getsize(fp)
            byte_range = (size * w // n, size * (w + 1) // n)

        def emit_rows(rows: list, line_seqs: list[int]) -> None:
            nonlocal chunk
            if not rows:
                return
            if meta is not None:
                for values in rows:
                    values["_metadata"] = dict(meta)
            # keys for the whole block in ONE native hash call
            if pk:
                key_args = [tuple(v[c] for c in pk) for v in rows]
                keys = keys_for_values(key_args)
            else:
                keys = None
                native = _native_mod.load()
                if native is not None:
                    try:
                        # prefix hash state computed once, per-row seq int
                        # appended in C — no per-row Python key tuples
                        keys = native.hash_prefix_ints(
                            ("__fs__", self.tag, fp), line_seqs, 1
                        )
                    except native.Unsupported:
                        keys = None
                if keys is None:
                    keys = keys_for_values(
                        ("__fs__", self.tag, fp, s + 1) for s in line_seqs
                    )
            coerced = coerce_rows(rows, schema)
            if add_many is None:
                for key, row in zip(keys, coerced):
                    events.add(key, row)
            else:
                chunk.extend(zip(keys, coerced))
                while len(chunk) >= _CHUNK:  # bounded add_many batches:
                    # one queue item / snapshot record per _CHUNK rows
                    add_many(chunk[:_CHUNK])
                    chunk = chunk[_CHUNK:]

        def parse_and_emit(complete: bytes) -> None:
            """Split once, keep only this worker's line share (disjoint
            line-index partition: each worker PARSES only 1/n of the
            input, unlike a post-parse key filter), parse, emit.

            Parsing runs in LINE-BOUNDED SUB-BATCHES: an 8MB block holds
            ~10^5 rows, and coercing + hashing all of them before the
            first emit keeps the engine idle for the whole parse (the
            epoch loop saw its first row only after ~70% of the run's
            wall time in the 2-process wordcount).  Emitting every ~32k
            lines overlaps the downstream epochs with the parse the way
            the reference's connector thread overlaps with its timely
            workers (src/connectors/mod.rs reader thread -> main loop)."""
            nonlocal seq, chunk
            lines = [ln for ln in complete.split(b"\n") if ln]
            base = seq
            seq = base + len(lines)
            if not lines:
                return
            emit_filter = False
            if byte_range is not None:
                # byte-range share: every line handed to us is owned
                owned_seqs: "list[int] | range" = range(
                    base, base + len(lines)
                )
                owned_lines = lines
            elif n > 1 and self._stateless_parser:
                # owned line indices form an arithmetic progression:
                # first index i with (base + i) % n == w, then every n-th
                first = (w - base) % n
                owned_seqs = range(base + first, base + len(lines), n)
                owned_lines = lines[first::n]
            else:
                owned_seqs = range(base, base + len(lines))
                owned_lines = lines
                emit_filter = n > 1  # stateful parser: filter after parse
            if not owned_lines:
                return
            _SUB = 32768
            for lo in range(0, len(owned_lines), _SUB):
                sub_lines = owned_lines[lo : lo + _SUB]
                sub_seqs = owned_seqs[lo : lo + _SUB]
                if frame_ok and not emit_filter and isinstance(sub_seqs, range):
                    # columnar fast path: one C pass parses the lines into
                    # a frame (typed columns, interned strings, lazy keys
                    # from the same prefix-hash the row path uses).  The
                    # row count must match exactly — a skipped/malformed
                    # line changes seq alignment, so the row path decides.
                    fr = _native.frame_parse_jsonl(
                        b"\n".join(sub_lines),
                        self.frame_plan,
                        frame_prefix,
                        sub_seqs.start,
                        sub_seqs.step,
                        1,
                    )
                    if fr is not None and _native.frame_len(fr) == len(
                        sub_lines
                    ):
                        if chunk:
                            # per-source event ORDER is the persistence
                            # resume contract: row chunks queued before
                            # this frame must enter the log first
                            add_many(chunk)
                            chunk = []
                        add_frame(fr)
                        continue
                rows = None
                if self.parse_block is not None and not emit_filter:
                    # (emit_filter set = stateful parser under n>1: only
                    # the per-line loop below applies the share filter)
                    rows = self.parse_block(b"\n".join(sub_lines))
                    if rows is not None and len(rows) != len(sub_lines):
                        # parser dropped lines: per-line path keeps the
                        # line-seq <-> row alignment exact, so row keys
                        # never depend on worker count
                        rows = None
                if rows is not None:
                    emit_rows(rows, list(sub_seqs))
                    continue
                out_rows: list = []
                out_seqs: list[int] = []
                for s, raw in zip(sub_seqs, sub_lines):
                    try:
                        values = parser(raw.decode(errors="replace"))
                    except Exception:
                        values = None  # unparseable line: skip
                    if isinstance(values, dict) and not (
                        emit_filter and s % n != w
                    ):
                        out_rows.append(values)
                        out_seqs.append(s)
                emit_rows(out_rows, out_seqs)

        # binary mode: byte-accurate offsets (text-mode tell() is unusable
        # with block reads), splitting on b"\n"; only COMPLETE lines are
        # consumed in streaming mode (a writer mid-append retries later)
        with open(fp, "rb") as f:
            if byte_range is not None:
                lo, hi = byte_range
                start = 0
                if lo > 0:
                    # a line spanning the lo boundary belongs to the
                    # worker owning its first byte: skip to the first line
                    # START at/after lo.  Seeking to lo-1 makes a boundary
                    # landing exactly on a line start discard nothing (the
                    # byte at lo-1 is then the previous line's newline).
                    start = size  # no line starts here: emit nothing
                    f.seek(lo - 1)
                    probe = lo - 1
                    while True:
                        data = f.read(_BLOCK)
                        if not data:
                            break
                        nl = data.find(b"\n")
                        if nl >= 0:
                            start = probe + nl + 1
                            break
                        probe += len(data)
                f.seek(0)
                seq = _nonempty_lines_before(f, start, _BLOCK)
                f.seek(start)
                offset = start
                while offset < hi:
                    data = f.read(_BLOCK)
                    if not data:
                        break
                    at_eof = len(data) < _BLOCK
                    cut = -1
                    if offset + len(data) > hi:
                        # the line containing byte hi-1 is the last one
                        # owned; consume through its newline and stop
                        cut = data.find(b"\n", hi - 1 - offset)
                    if cut >= 0:
                        complete = data[: cut + 1]
                        f.seek(offset + len(complete))
                    elif at_eof:
                        complete = data  # static: unterminated tail too
                    else:
                        nl = data.rfind(b"\n")
                        if nl < 0:
                            # single line longer than the block: keep
                            # reading until its newline (or EOF)
                            parts = [data]
                            while True:
                                more = f.read(_BLOCK)
                                if not more:
                                    break
                                mnl = more.find(b"\n")
                                if mnl >= 0:
                                    parts.append(more[: mnl + 1])
                                    break
                                parts.append(more)
                            complete = b"".join(parts)
                        else:
                            complete = data[: nl + 1]
                        f.seek(offset + len(complete))
                    parse_and_emit(complete)
                    offset += len(complete)
                    if cut >= 0:
                        break
                if chunk:
                    add_many(chunk)
                return size, seq
            f.seek(start_offset)
            offset = start_offset
            while True:
                data = f.read(_BLOCK)
                if not data:
                    break
                at_eof = len(data) < _BLOCK
                if at_eof and self.mode == "static":
                    complete = data  # static: consume the unterminated tail too
                else:
                    nl = data.rfind(b"\n")
                    if nl < 0:
                        # a single line longer than the block: keep reading
                        # until its newline (or EOF) so the offset can
                        # advance — breaking here would re-read the same
                        # block forever in streaming mode
                        parts = [data]
                        while True:
                            more = f.read(_BLOCK)
                            if not more:
                                at_eof = True
                                break
                            nl = more.find(b"\n")
                            if nl >= 0:
                                parts.append(more[: nl + 1])
                                break
                            parts.append(more)
                        if at_eof and self.mode != "static":
                            break  # unterminated giant line: retry later
                        data = b"".join(parts)
                        complete = data
                        f.seek(offset + len(complete))
                    else:
                        complete = data[: nl + 1]
                        if nl + 1 < len(data):
                            f.seek(offset + len(complete))
                parse_and_emit(complete)
                offset += len(complete)
                if at_eof:
                    break
            if chunk:
                add_many(chunk)
            return offset, seq

    def run(self, events: Any) -> None:
        offsets: dict[str, int] = {}
        seqs: dict[str, int] = {}
        parsers: dict[str, Callable] = {}
        while True:
            emitted = False
            for fp in _list_files(self.path):
                start = offsets.get(fp, 0)
                try:
                    size = os.path.getsize(fp)
                except OSError:
                    continue
                if size > start:
                    if fp not in parsers:
                        parsers[fp] = self.parser_factory(fp)
                    offsets[fp], seqs[fp] = self._emit_file(
                        events, fp, start, seqs.get(fp, 0), parsers[fp]
                    )
                    emitted = True
            if emitted:
                events.commit()
            if self.mode == "static":
                return
            if events.stopped:
                return
            _time.sleep(self.poll_interval)


class _WholeFileSource(RowSource):
    """One row PER FILE (``format="binary"`` / ``"plaintext_by_file"``,
    reference binary object pattern): streaming mode polls the directory
    and upserts changed files (keyed by path) and retracts deleted ones —
    the dir-watch contract DocumentStore ingestion relies on."""

    #: the sorted dir scan re-produces events in the same order on a
    #: resume-from-snapshot restart (same contract as _FilesSource)
    deterministic_replay = True

    def __init__(
        self,
        path: str,
        schema: sch.SchemaMetaclass,
        *,
        binary: bool,
        mode: str,
        poll_interval: float = 0.2,
        with_metadata: bool = False,
    ):
        self.path = path
        self.schema = schema
        self.binary = binary
        self.mode = mode
        self.poll_interval = poll_interval
        self.with_metadata = with_metadata

    def _row(self, fp: str, payload: Any, mtime: float = 0.0) -> dict:
        values: dict[str, Any] = {"data": payload}
        if self.with_metadata:
            values["_metadata"] = {
                "path": fp,
                "modified_at": int(mtime),
            }
        return values

    def run(self, events: Any) -> None:
        seen: dict[str, tuple[float, int]] = {}  # path -> (mtime, size)
        while True:
            changed = False
            current = set()
            for fp in _list_files(self.path):
                current.add(fp)
                try:
                    st = os.stat(fp)
                    sig = (st.st_mtime, st.st_size)
                    if seen.get(fp) == sig:
                        continue
                    with open(fp, "rb") as f:
                        data = f.read()
                except OSError:
                    continue  # raced with deletion: next poll retracts
                payload: Any = (
                    data if self.binary else data.decode("utf-8", "replace")
                )
                events.add(
                    ref_scalar("__fsbin__", fp),
                    coerce_row(
                        self._row(fp, payload, st.st_mtime), self.schema
                    ),
                )
                seen[fp] = sig
                changed = True
            for fp in list(seen):
                if fp not in current:
                    del seen[fp]
                    events.remove(
                        ref_scalar("__fsbin__", fp),
                        coerce_row(
                            self._row(fp, b"" if self.binary else ""),
                            self.schema,
                        ),
                    )
                    changed = True
            if changed:
                events.commit()
            if self.mode == "static":
                return
            deadline = _time.monotonic() + self.poll_interval
            while _time.monotonic() < deadline:
                if events.stopped:
                    return
                _time.sleep(min(0.05, self.poll_interval))


def read(
    path: str | os.PathLike,
    *,
    format: str = "plaintext",
    schema: sch.SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str = "fs",
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format in ("binary", "plaintext_by_file"):
        # whole-file rows (reference binary/plaintext_by_file object
        # pattern): the natural source for DocumentStore pipelines
        binary = format == "binary"
        if schema is None:
            cols: dict[str, Any] = {"data": bytes if binary else str}
            if with_metadata:
                cols["_metadata"] = dict
            schema = sch.schema_from_types(**cols)
        wsrc = _WholeFileSource(
            str(path), schema, binary=binary, mode=mode,
            with_metadata=with_metadata,
            poll_interval=kwargs.get("poll_interval", 0.2),
        )
        return input_table(
            wsrc, schema, name=name, persistent_id=persistent_id,
            upsert=True,
        )
    if format == "plaintext":
        if schema is None:
            schema = sch.schema_from_types(data=str)

        def parse_plain(line: str) -> dict | None:
            line = line.rstrip("\n")
            return {"data": line} if line else None

        src = _FilesSource(
            str(path), schema, parse_line=parse_plain, mode=mode,
            with_metadata=with_metadata, tag=f"fs:{path}",
        )
        return input_table(
            src, schema, name=name, persistent_id=persistent_id
        )
    if format == "json" or format == "jsonlines":
        from pathway_tpu.io import jsonlines

        return jsonlines.read(
            path, schema=schema, mode=mode, name=name,
            with_metadata=with_metadata, **kwargs
        )
    if format == "csv":
        from pathway_tpu.io import csv as csv_io

        return csv_io.read(
            path, schema=schema, mode=mode, name=name,
            csv_settings=csv_settings, with_metadata=with_metadata, **kwargs
        )
    raise ValueError(f"unsupported fs format {format!r}")


class _PlainWriter(LazyFileWriter):
    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        vals = {k: fmt_value(v) for k, v in row.items() if k != "id"}
        import json

        vals["time"] = time
        vals["diff"] = diff
        self._file().write(json.dumps(vals) + "\n")



def write(table: Table, filename: str | os.PathLike, format: str = "json", **kwargs: Any) -> None:
    if format in ("json", "jsonlines"):
        from pathway_tpu.io import jsonlines

        jsonlines.write(table, filename)
        return
    if format == "csv":
        from pathway_tpu.io import csv as csv_io

        csv_io.write(table, filename)
        return
    attach_writer(table, _PlainWriter(str(filename)))
