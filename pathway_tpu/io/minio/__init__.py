"""``pw.io.minio`` — MinIO connector (reference ``python/pathway/io/minio``).

MinIO speaks the S3 protocol: settings wrap an endpoint + path-style
addressing and delegate to :mod:`pathway_tpu.io.s3`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io import s3 as _s3

__all__ = ["MinIOSettings", "read"]


class MinIOSettings:
    """reference ``pw.io.minio.MinIOSettings``."""

    def __init__(
        self,
        endpoint: str | None = None,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        *,
        with_path_style: bool = True,
        region: str | None = None,
        client: Any = None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region
        self._client = client

    def create_aws_settings(self) -> _s3.AwsS3Settings:
        return _s3.AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            with_path_style=self.with_path_style,
            region=self.region,
            endpoint=self.endpoint,
            client=self._client,
        )


def read(
    path: str,
    minio_settings: MinIOSettings,
    *,
    format: str = "jsonlines",
    **kwargs: Any,
) -> Table:
    return _s3.read(
        path,
        aws_s3_settings=minio_settings.create_aws_settings(),
        format=format,
        name=kwargs.pop("name", "minio"),
        **kwargs,
    )
