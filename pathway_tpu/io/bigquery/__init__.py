"""``pw.io.bigquery`` — BigQuery sink (reference
``python/pathway/io/bigquery``).

Each epoch's updates flush as one ``insert_rows_json`` batch; rows carry
``time``/``diff`` fields exactly like the reference contract (a modified
row arrives as a -1 row then a +1 row).  The client is injectable
(anything with ``insert_rows_json(table_ref, rows)``); without one,
``google.cloud.bigquery.Client`` is constructed from the service-user
credentials file.
"""

from __future__ import annotations

import json
from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer, format_change_row
from pathway_tpu.io._gated import MissingDependency

__all__ = ["write"]


class _BigQueryWriter(Writer):
    def __init__(
        self,
        dataset_name: str,
        table_name: str,
        credentials_file: str | None,
        client: Any,
    ):
        self.table_ref = f"{dataset_name}.{table_name}"
        self.credentials_file = credentials_file
        self._client = client
        self._rows: list[dict] = []

    def _get_client(self) -> Any:
        if self._client is None:
            try:
                from google.cloud import bigquery  # type: ignore[import-not-found]
            except ImportError as e:
                raise MissingDependency(
                    "google-cloud-bigquery is not installed; pass client= "
                    "with an insert_rows_json-capable object"
                ) from e
            if self.credentials_file:
                self._client = bigquery.Client.from_service_account_json(
                    self.credentials_file
                )
            else:  # application-default credentials
                self._client = bigquery.Client()
        return self._client

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        doc = {k: _json_safe(v) for k, v in format_change_row(row, time, diff).items()}
        self._rows.append(doc)

    def flush(self) -> None:
        if not self._rows:
            return
        errors = self._get_client().insert_rows_json(self.table_ref, self._rows)
        if errors:
            raise RuntimeError(f"BigQuery insert failed: {errors}")
        self._rows = []


def _json_safe(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    *,
    client: Any = None,
    name: str = "bigquery_out",
) -> None:
    """Write the table's change stream to a BigQuery table (whose schema
    must include integral ``time`` and ``diff`` fields)."""
    attach_writer(
        table,
        _BigQueryWriter(dataset_name, table_name, service_user_credentials_file, client),
        name=name,
    )
