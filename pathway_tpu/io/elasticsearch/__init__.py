"""``pw.io.elasticsearch`` — Elasticsearch sink (reference
``python/pathway/io/elasticsearch``; writer ``ElasticSearchWriter``
``src/connectors/data_storage.rs:1336``).

Each epoch's updates are flushed as one bulk request: additions index a
JSON document (the engine row key as ``_id``), retractions delete it.
The client is injectable (anything with ``bulk(operations=[...])``, e.g.
``elasticsearch.Elasticsearch``/test doubles); otherwise the official
client is imported lazily.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import Writer, attach_writer, fmt_key, fmt_value
from pathway_tpu.io._gated import MissingDependency

__all__ = ["write", "ElasticSearchAuth"]


class ElasticSearchAuth:
    """reference ``pw.io.elasticsearch.ElasticSearchAuth`` (basic/apikey)."""

    def __init__(self, kind: str, **params: Any):
        self.kind = kind
        self.params = params

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", basic_auth=(username, password))

    @classmethod
    def apikey(cls, api_key: str, api_key_id: str | None = None) -> "ElasticSearchAuth":
        key = (api_key_id, api_key) if api_key_id else api_key
        return cls("apikey", api_key=key)


class _ElasticWriter(Writer):
    def __init__(self, host: str, auth: ElasticSearchAuth | None, index_name: str, client: Any):
        self.host = host
        self.auth = auth
        self.index_name = index_name
        self._client = client
        self._ops: list[dict] = []

    def _get_client(self) -> Any:
        if self._client is None:
            try:
                from elasticsearch import Elasticsearch  # type: ignore[import-not-found]
            except ImportError as e:
                raise MissingDependency(
                    "elasticsearch client is not installed; pass client= "
                    "with a bulk()-capable client"
                ) from e
            kwargs = dict(self.auth.params) if self.auth else {}
            self._client = Elasticsearch(self.host, **kwargs)
        return self._client

    def write(self, row: dict[str, Any], time: int, diff: int) -> None:
        # canonical key form shared with every other sink (fmt_key), so
        # _ids correlate with pointer columns in any output
        doc_id = fmt_key(row.get("id"))
        if diff > 0:
            doc = {k: fmt_value(v) for k, v in row.items() if k != "id"}
            doc["time"] = time
            self._ops.append(
                {"index": {"_index": self.index_name, "_id": doc_id}}
            )
            self._ops.append(doc)
        else:
            self._ops.append(
                {"delete": {"_index": self.index_name, "_id": doc_id}}
            )

    def flush(self) -> None:
        if not self._ops:
            return
        self._get_client().bulk(operations=self._ops)
        self._ops = []

    def close(self) -> None:
        self.flush()


def write(
    table: Table,
    host: str,
    auth: ElasticSearchAuth | None,
    index_name: str,
    *,
    client: Any = None,
    name: str = "elasticsearch_out",
    **kwargs: Any,
) -> None:
    attach_writer(table, _ElasticWriter(host, auth, index_name, client), name=name)
