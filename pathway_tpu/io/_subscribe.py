"""``pw.io.subscribe`` (reference ``python/pathway/io/_subscribe.py``)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = ["subscribe", "OnChangeCallback", "OnFinishCallback"]

OnChangeCallback = Callable[..., Any]
OnFinishCallback = Callable[[], Any]


def subscribe(
    table: Table,
    on_change: Callable[[Pointer, dict, int, bool], Any] | None = None,
    on_end: Callable[[], Any] | None = None,
    on_time_end: Callable[[int], Any] | None = None,
    *,
    name: str = "subscribe",
    sort_by: Any = None,
) -> eg.OutputNode:
    """Call ``on_change(key, row: dict, time: int, is_addition: bool)`` for
    every update of ``table``; ``on_time_end(time)`` at every closed epoch;
    ``on_end()`` when the stream finishes.  Returns the sink node so
    callers can annotate ``node.meta`` for the analyzer."""
    cols = table._column_names

    def _on_change(key: Pointer, values: tuple, time: int, diff: int) -> None:
        if on_change is not None:
            on_change(key, dict(zip(cols, values)), time, diff > 0)

    return eg.OutputNode(
        G.engine_graph,
        table._node,
        _on_change if on_change else None,
        on_time_end,
        on_end,
        name=name,
    )
