"""``pw.demo`` — synthetic streams (reference ``python/pathway/demo/``:
``generate_custom_stream`` ``:28``, ``noisy_linear_stream`` ``:118``,
``range_stream``, ``replay_csv``)."""

from __future__ import annotations

import random
import time as _time
from typing import Any, Callable, Mapping

from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table
from pathway_tpu.io._connector import DictSource, input_table

__all__ = [
    "generate_custom_stream",
    "noisy_linear_stream",
    "range_stream",
    "replay_csv",
    "replay_csv_with_time",
]


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: sch.SchemaMetaclass,
    nb_rows: int | None = None,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 20,
    persistent_id: str | None = None,
    name: str = "demo",
) -> Table:
    """Stream rows produced by per-column generator functions of the row
    index, at ``input_rate`` rows/sec (None ``nb_rows`` = infinite)."""

    def rows():
        i = 0
        delay = 1.0 / input_rate if input_rate > 0 else 0.0
        while nb_rows is None or i < nb_rows:
            yield {name_: gen(i) for name_, gen in value_generators.items()}
            i += 1
            if delay:
                _time.sleep(delay)

    src = DictSource(
        rows,
        schema,
        commit_interval=autocommit_duration_ms / 1000.0,
        commit_every=1 if input_rate <= 100 else 64,
        tag=name,
    )
    return input_table(src, schema, name=name)


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0, **kwargs: Any) -> Table:
    schema = sch.schema_from_types(x=float, y=float)
    rng = random.Random(0)

    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + (2 * rng.random() - 1) / 10,
        },
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
        name="noisy_linear",
    )


def range_stream(
    nb_rows: int = 30, offset: int = 0, input_rate: float = 1.0, **kwargs: Any
) -> Table:
    schema = sch.schema_from_types(value=float)
    return generate_custom_stream(
        {"value": lambda i: float(i + offset)},
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
        name="range",
    )


def replay_csv(
    path: str,
    *,
    schema: sch.SchemaMetaclass,
    input_rate: float = 1.0,
    **kwargs: Any,
) -> Table:
    """Replay a CSV file as a stream at ``input_rate`` rows/sec."""
    import csv as _csv

    def rows():
        delay = 1.0 / input_rate if input_rate > 0 else 0.0
        with open(path) as f:
            for row in _csv.DictReader(f):
                yield dict(row)
                if delay:
                    _time.sleep(delay)

    src = DictSource(rows, schema, commit_every=1, tag=f"replay:{path}")
    return input_table(src, schema, name="replay_csv")


def replay_csv_with_time(
    path: str,
    *,
    schema: sch.SchemaMetaclass,
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1.0,
    **kwargs: Any,
) -> Table:
    """Replay a CSV using the recorded time column for pacing."""
    import csv as _csv

    scale = {"s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}[unit]

    def rows():
        prev_t: float | None = None
        with open(path) as f:
            for row in _csv.DictReader(f):
                t = float(row[time_column]) * scale
                if prev_t is not None and t > prev_t:
                    _time.sleep((t - prev_t) / speedup)
                prev_t = t
                yield dict(row)

    src = DictSource(rows, schema, commit_interval=autocommit_ms / 1000.0, tag=f"replay:{path}")
    return input_table(src, schema, name="replay_csv_with_time")
