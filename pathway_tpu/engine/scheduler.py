"""Epoch scheduler: the engine main loop.

Equivalent of the reference worker main loop (``run_with_new_dataflow_graph``
+ ``step_or_park`` + pollers/flushers, ``src/engine/dataflow.rs:5506-5717``):
drains connector event queues, cuts consistent epochs (micro-batches), and
propagates update batches through the node graph in topological order.

Consistency contract: outputs observe only closed epochs — within an epoch
every operator sees the complete batch, so downstream tables are always a
consistent snapshot (same guarantee the reference gets from timely frontiers).
"""

from __future__ import annotations

import queue
import threading
import time as _time
from collections import defaultdict
from typing import Any

from pathway_tpu.engine.graph import EngineGraph, InputNode, Node, RunContext
from pathway_tpu.engine.stream import TIME_STEP, Batch, Update
from pathway_tpu.internals.keys import Pointer


class ConnectorEvents:
    """Callback bundle handed to a connector subject's reader thread."""

    #: with persistence, the number of already-replayed events this reader
    #: should skip (cooperative resume; see pathway_tpu.persistence)
    resume_offset: int = 0

    def __init__(
        self,
        q: "queue.Queue",
        node_id: int,
        stop_event: threading.Event | None = None,
    ):
        self._q = q
        self._node_id = node_id
        self._stop_event = stop_event

    @property
    def stopped(self) -> bool:
        """True once the scheduler is shutting down; readers should return."""
        return self._stop_event is not None and self._stop_event.is_set()

    def add(self, key: Pointer, values: tuple) -> None:
        self._q.put((self._node_id, "add", key, values))

    def remove(self, key: Pointer, values: tuple) -> None:
        self._q.put((self._node_id, "remove", key, values))

    def commit(self) -> None:
        self._q.put((self._node_id, "commit", None, None))

    def close(self) -> None:
        self._q.put((self._node_id, "close", None, None))


class Scheduler:
    def __init__(
        self,
        graph: EngineGraph,
        *,
        autocommit_ms: int = 50,
        n_workers: int = 1,
        worker_id: int = 0,
    ):
        self.graph = graph
        self.autocommit_ms = autocommit_ms
        self.consumers: dict[int, list[tuple[Node, int]]] = defaultdict(list)
        for node in graph.nodes:
            for port, inp in enumerate(node.inputs):
                self.consumers[inp.id].append((node, port))
        self.ctx = RunContext(n_workers=n_workers, worker_id=worker_id)
        self._stop = threading.Event()
        #: persistence hooks (set by pathway_tpu.persistence.attach_persistence)
        self.persistence: Any = None

    # ------------------------------------------------------------------
    def run_epoch(self, time: int, inject: dict[int, Batch]) -> None:
        ctx = self.ctx
        ctx.time = time
        pending: dict[int, dict[int, list[Update]]] = defaultdict(lambda: defaultdict(list))
        for nid, batch in inject.items():
            pending[nid][0] = list(batch)
        for node in self.graph.nodes:
            ins = pending.pop(node.id, None)
            has_input = ins is not None and any(ins.values())
            if not has_input and not node.always_tick and not getattr(ctx, "finalizing", False):
                continue
            n_ports = max(1, len(node.inputs))
            inbatches = [ins.get(i, []) if ins else [] for i in range(n_ports)]
            out = node.process(ctx, time, inbatches)
            if out:
                for consumer, port in self.consumers.get(node.id, ()):  # fan-out
                    pending[consumer.id][port].extend(out)
        for node in self.graph.nodes:
            node.on_time_end(ctx, time)

    def _finish(self) -> None:
        # final flush epoch: frontier advances to +inf; buffering operators release
        self.ctx.finalizing = True  # type: ignore[attr-defined]
        self.run_epoch(self.ctx.time + TIME_STEP, {})
        for node in self.graph.nodes:
            node.on_end(self.ctx)

    # ------------------------------------------------------------------
    def run(self) -> RunContext:
        static_inject: dict[int, Batch] = {}
        live_inputs: list[InputNode] = []
        for node in self.graph.nodes:
            if isinstance(node, InputNode):
                if node.static_rows:
                    static_inject[node.id] = [
                        Update(k, v, 1) for k, v in node.static_rows
                    ]
                if node.subject is not None:
                    live_inputs.append(node)

        if not live_inputs:
            self.run_epoch(0, static_inject)
            self.ctx.time = 0
            self._finish()
            return self.ctx

        # --- streaming mode -------------------------------------------
        t = 0
        if static_inject:
            self.run_epoch(t, static_inject)
            t += TIME_STEP

        # persistence: replay committed input snapshots as leading epochs
        replayed_counts: dict[int, int] = {}
        if self.persistence is not None:
            for node in live_inputs:
                events = self.persistence.replay_events(node)
                replayed_counts[node.id] = sum(
                    1 for kind, _k, _v in events if kind != "commit"
                )
                epoch: list[Update] = []
                for kind, key, values in events:
                    if kind == "add":
                        epoch.append(Update(key, values, 1))
                    elif kind == "remove":
                        epoch.append(Update(key, values, -1))
                    elif kind == "commit" and epoch:
                        self.run_epoch(t, {node.id: epoch})
                        t += TIME_STEP
                        epoch = []
            if self.persistence.replay_only:
                self.ctx.time = t
                self._finish()
                return self.ctx

        q: "queue.Queue" = queue.Queue()
        threads: list[threading.Thread] = []
        for node in live_inputs:
            events: Any = ConnectorEvents(q, node.id, self._stop)
            if self.persistence is not None:
                events = self.persistence.wrap_events(
                    node, events, replayed_counts.get(node.id, 0)
                )
            t_ = threading.Thread(
                target=self._run_subject, args=(node, events), daemon=True
            )
            t_.start()
            threads.append(t_)

        # auxiliary inputs (loopbacks) never keep the run alive by
        # themselves: the run ends when all primaries closed AND every
        # auxiliary reports no pending work
        primaries = [n for n in live_inputs if not getattr(n, "auxiliary", False)]
        auxiliaries = [n for n in live_inputs if getattr(n, "auxiliary", False)]
        open_subjects = {n.id for n in primaries}
        buffers: dict[int, list[Update]] = defaultdict(list)
        last_cut = _time.monotonic()
        commit_requested = False
        while True:
            timeout = self.autocommit_ms / 1000.0
            try:
                nid, kind, key, values = q.get(timeout=timeout)
                if kind == "add":
                    buffers[nid].append(Update(key, values, 1))
                elif kind == "remove":
                    buffers[nid].append(Update(key, values, -1))
                elif kind == "commit":
                    commit_requested = True
                elif kind == "close":
                    open_subjects.discard(nid)
            except queue.Empty:
                pass
            now = _time.monotonic()
            have_data = any(buffers.values())
            should_cut = have_data and (
                commit_requested or (now - last_cut) * 1000.0 >= self.autocommit_ms
            )
            if should_cut:
                inject = {nid: b for nid, b in buffers.items() if b}
                buffers = defaultdict(list)
                commit_requested = False
                self.run_epoch(t, inject)
                t += TIME_STEP
                last_cut = now
            if not open_subjects and not any(buffers.values()):
                # order matters: loopback workers enqueue their result BEFORE
                # decrementing pending, so pending==0 guarantees every result
                # is already visible to the q.empty() check after it
                pending = sum(
                    getattr(n.subject, "pending_count", lambda: 0)()
                    for n in auxiliaries
                )
                if pending == 0 and q.empty():
                    break
            if self._stop.is_set():
                break
        self.ctx.time = t
        self._finish()
        return self.ctx

    @staticmethod
    def _run_subject(node: InputNode, events: ConnectorEvents) -> None:
        try:
            node.subject.run(events)
        except Exception as e:  # reader errors must not hang the run
            import logging

            logging.getLogger("pathway_tpu").error(
                "connector %s failed: %r", node.name, e
            )
        finally:
            events.close()

    def stop(self) -> None:
        self._stop.set()
