"""Epoch scheduler: the engine main loop, single- and multi-worker.

Equivalent of the reference worker main loop (``run_with_new_dataflow_graph``
+ ``step_or_park`` + pollers/flushers, ``src/engine/dataflow.rs:5506-5717``):
drains connector event queues, cuts consistent epochs (micro-batches), and
propagates update batches through the node graph in topological order.

Consistency contract: outputs observe only closed epochs — within an epoch
every operator sees the complete batch, so downstream tables are always a
consistent snapshot (same guarantee the reference gets from timely frontiers).

Multi-worker mode (reference ``PATHWAY_THREADS`` × ``PATHWAY_PROCESSES``,
``src/engine/dataflow/config.rs:86-120``): every worker runs the identical
node list over its own :class:`RunContext`; at stateful operators the epoch
batch is exchanged by a stable key hash (``Node.exchange_routes``) so each
worker owns a disjoint state shard.  Epoch cuts are agreed by an allgather
of worker statuses + an identical pure decision function — the epoch-
synchronous analogue of timely progress tracking.
"""

from __future__ import annotations

import os as _os
import queue
import threading
import time as _time
from collections import defaultdict, deque
from typing import Any, Callable

from pathway_tpu.engine.cluster import Cluster, epoch_trace_context
from pathway_tpu.engine.columnar import ColumnarBatch, extend_batch
from pathway_tpu.engine.graph import EngineGraph, InputNode, Node, RunContext
from pathway_tpu.engine.stream import TIME_STEP, Batch, Update
from pathway_tpu.internals import api
from pathway_tpu.internals import native as _native
from pathway_tpu.internals import tracing as _tracing
from pathway_tpu.internals.keys import Pointer

def _build_adds(rows: Any) -> list:
    """Bulk ``Update(key, values, +1)`` construction (static-row injection
    is a million-row listcomp of NamedTuple calls in big debug tables)."""
    native = _native.load()
    if native is not None:
        try:
            return native.build_adds(rows, Update)
        except Exception:
            pass
    return [Update(k, v, 1) for k, v in rows]


#: dev knob: per-round cluster trace on stderr (timing the epoch loop)
_EPOCH_TRACE = _os.environ.get("PATHWAY_EPOCH_TRACE") == "1"

#: entries sampled per container level when measuring operator state
_STATE_SAMPLE = 24


def approx_state_bytes(obj: Any, depth: int = 5) -> int:
    """Sampled deep size of an operator's state: containers extrapolate
    from their first ``_STATE_SAMPLE`` entries (state dicts are
    homogeneous — groups, kept rows, join sides), numpy buffers report
    ``nbytes``.  Bounds the per-sample cost regardless of state size;
    feeds ``pathway_tpu_state_bytes{operator}`` next to the static
    estimate for cross-validation."""
    import sys

    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        try:
            return int(nb) + 16
        except (TypeError, ValueError):
            pass
    try:
        base = sys.getsizeof(obj)
    except TypeError:
        return 64
    if depth <= 0:
        return base
    if isinstance(obj, dict):
        n = len(obj)
        if not n:
            return base
        tot = k = 0
        for key, val in obj.items():
            tot += approx_state_bytes(key, depth - 1)
            tot += approx_state_bytes(val, depth - 1)
            k += 1
            if k >= _STATE_SAMPLE:
                break
        return base + int(tot / k * n)
    if isinstance(obj, (list, tuple, set, frozenset)):
        n = len(obj)
        if not n:
            return base
        tot = k = 0
        for val in obj:
            tot += approx_state_bytes(val, depth - 1)
            k += 1
            if k >= _STATE_SAMPLE:
                break
        return base + int(tot / k * n)
    return base


#: default bound on bytes buffered between the connector readers and the
#: epoch drain (PATHWAY_INGEST_BUFFER_BYTES); <= 0 disables accounting
DEFAULT_INGEST_BUFFER_BYTES = 256 << 20

#: per-connector overflow policies (input_table(on_overflow=...))
INGEST_OVERFLOW_MODES = ("pause", "shed_oldest", "fail")


class IngestOverflow(RuntimeError):
    """Raised into the reader thread when its source overflows the ingest
    buffer under ``on_overflow="fail"`` (the supervisor applies the
    connector's recovery policy to it like any other reader failure)."""


def _approx_event_bytes(kind: str, key: Any, values: Any) -> int:
    """Cheap buffered-size estimate of one queue item.  Batch items hold
    the built Update list in ``key``; sampled sizing extrapolates, so a
    million-row chunk costs a bounded probe, not a deep walk."""
    if kind == "batch":
        return approx_state_bytes(key, depth=3) + 64
    if kind == "frame":
        native = _native.load()
        return (native.frame_nbytes(key) if native is not None else 0) + 64
    return approx_state_bytes(values, depth=2) + 96


class IngestCredit:
    """Bytes-accounted admission for the connector -> scheduler queue.

    One instance per scheduler, shared by every source: readers *charge*
    each data item before enqueueing it and the drain loops *consume* it
    when it leaves the queue, so the un-drained backlog is bounded by
    ``capacity_bytes`` end to end.  Overflow behaviour is per source:

    - ``"pause"`` (default): the reader thread parks in finite wait
      slices until the drain frees room — native backpressure, no loss.
      A paused source is flagged in its connector stats so the
      supervisor's watchdog does not mistake backpressure for a hang.
    - ``"shed_oldest"``: the source's oldest *buffered* items are
      uncharged immediately (a shed floor advances past them) and the
      drain discards them when it reaches them — counted shed, never
      silent loss.
    - ``"fail"``: raises :class:`IngestOverflow` into the reader.

    All waits are finite condition slices re-checking the stop event, so
    shutdown always interrupts a paused reader."""

    _WAIT_SLICE_S = 0.05

    def __init__(self, capacity_bytes: int):
        self.capacity = int(capacity_bytes)
        self._cv = threading.Condition()
        #: per-source FIFO of (seq, bytes, rows) still in the queue
        self._entries: dict[int, deque] = {}
        self._next_seq: dict[int, int] = {}
        #: items with seq < floor were shed; the drain skips them
        self._floor: dict[int, int] = {}
        self._bytes: dict[int, int] = {}
        self._rows: dict[int, int] = {}
        self._total = 0
        self.stalls_total = 0
        self.stall_ms_total = 0.0
        self.shed_rows: dict[int, int] = {}
        self.shed_bytes: dict[int, int] = {}
        self._paused: set[int] = set()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def level(self) -> float:
        """Buffer occupancy in [0, 1] — the engine's ingest-pressure
        signal (pushed to serving brownout when the gap is material)."""
        if self.capacity <= 0:
            return 0.0
        return min(1.0, self._total / self.capacity)

    def charge(
        self,
        node_id: int,
        nbytes: int,
        nrows: int,
        on_overflow: str,
        stop_event: threading.Event | None,
        stats: dict | None = None,
    ) -> int:
        """Admit one data item; returns its sequence number.  May block
        (pause), advance the shed floor (shed_oldest), or raise
        (:class:`IngestOverflow`, fail)."""
        t0 = _time.monotonic()
        stalled = False
        with self._cv:
            while (
                self._total > 0
                and self._total + nbytes > self.capacity
                and not (stop_event is not None and stop_event.is_set())
            ):
                if on_overflow == "fail":
                    raise IngestOverflow(
                        f"source {node_id} overflowed the ingest buffer "
                        f"({self._total + nbytes} > {self.capacity} bytes; "
                        f"PATHWAY_INGEST_BUFFER_BYTES)"
                    )
                if on_overflow == "shed_oldest":
                    if not self._shed_locked(node_id, nbytes):
                        break  # nothing of ours left to shed: admit over
                    continue
                # pause: finite slices; the drain's consume notifies
                if not stalled:
                    stalled = True
                    self.stalls_total += 1
                    self._paused.add(node_id)
                    if stats is not None:
                        stats["paused"] = True
                        stats["pauses"] = stats.get("pauses", 0) + 1
                self._cv.wait(self._WAIT_SLICE_S)
            if stalled:
                self._paused.discard(node_id)
                if stats is not None:
                    stats["paused"] = False
                self.stall_ms_total += (_time.monotonic() - t0) * 1e3
            seq = self._next_seq.get(node_id, 0)
            self._next_seq[node_id] = seq + 1
            self._entries.setdefault(node_id, deque()).append(
                (seq, nbytes, nrows)
            )
            self._bytes[node_id] = self._bytes.get(node_id, 0) + nbytes
            self._rows[node_id] = self._rows.get(node_id, 0) + nrows
            self._total += nbytes
            return seq

    def _shed_locked(self, node_id: int, need: int) -> bool:
        """Uncharge this source's oldest buffered items until ``need``
        bytes fit (or nothing of ours is left); the floor marks them for
        the drain to discard.  Returns True if anything was shed."""
        entries = self._entries.get(node_id)
        if not entries:
            return False
        shed_any = False
        while entries and self._total + need > self.capacity:
            seq, nbytes, nrows = entries.popleft()
            self._floor[node_id] = seq + 1
            self._bytes[node_id] -= nbytes
            self._rows[node_id] -= nrows
            self._total -= nbytes
            self.shed_rows[node_id] = self.shed_rows.get(node_id, 0) + nrows
            self.shed_bytes[node_id] = (
                self.shed_bytes.get(node_id, 0) + nbytes
            )
            shed_any = True
        return shed_any

    def consume(self, node_id: int, seq: int) -> bool:
        """Called by the drain when an item leaves the queue; False means
        the item was shed (the drain discards it without processing)."""
        with self._cv:
            if seq < self._floor.get(node_id, 0):
                return False  # shed: bytes already uncharged
            entries = self._entries.get(node_id)
            if entries and entries[0][0] == seq:
                _s, nbytes, nrows = entries.popleft()
                self._bytes[node_id] -= nbytes
                self._rows[node_id] -= nrows
                self._total -= nbytes
                self._cv.notify_all()  # room freed: wake paused readers
            return True

    def snapshot(self) -> dict[int, dict]:
        """Per-source occupancy + shed counters (node-id keyed; the
        scheduler maps ids to input names for /metrics)."""
        with self._cv:
            out: dict[int, dict] = {}
            for nid in set(self._bytes) | set(self.shed_rows):
                out[nid] = {
                    "rows": self._rows.get(nid, 0),
                    "bytes": self._bytes.get(nid, 0),
                    "shed_rows": self.shed_rows.get(nid, 0),
                    "shed_bytes": self.shed_bytes.get(nid, 0),
                    "paused": nid in self._paused,
                }
            return out

    def totals(self) -> dict[str, Any]:
        with self._cv:
            return {
                "capacity_bytes": self.capacity,
                "buffered_bytes": self._total,
                "buffered_rows": sum(self._rows.values()),
                "stalls_total": self.stalls_total,
                "stall_ms_total": round(self.stall_ms_total, 3),
                "shed_rows_total": sum(self.shed_rows.values()),
                "paused_sources": len(self._paused),
                "level": self.level(),
            }


def _buffer_frame(buffers: dict, nid: int, cap: Any) -> None:
    """Append a native frame to a per-source drain buffer, promoting the
    plain row list to a :class:`ColumnarBatch` on first frame arrival."""
    buf = buffers[nid]
    if not isinstance(buf, ColumnarBatch):
        buf = ColumnarBatch.from_rows(buf)
        buffers[nid] = buf
    buf.append_frame(cap)


class ConnectorEvents:
    """Callback bundle handed to a connector subject's reader thread.

    Every event carries a monotonic enqueue timestamp (5th tuple element)
    so the scheduler's drain can measure queue residency (the "ingest"
    latency stage), and every enqueue fires the optional ``wake`` hook —
    in cluster mode that is the :class:`~pathway_tpu.engine.cluster.
    WakeupHub`, so a parked worker loop reacts to arrival instead of
    discovering it on the next poll tick."""

    #: with persistence, the number of already-replayed events this reader
    #: should skip (cooperative resume; see pathway_tpu.persistence)
    resume_offset: int = 0

    def __init__(
        self,
        q: "queue.Queue",
        node_id: int,
        stop_event: threading.Event | None = None,
        stats: dict | None = None,
        now_ns: Callable[[], int] | None = None,
        wake: Callable[[], None] | None = None,
        credit: "IngestCredit | None" = None,
        on_overflow: str | None = None,
    ):
        self._q = q
        self._node_id = node_id
        self._stop_event = stop_event
        self._now_ns = now_ns if now_ns is not None else _time.monotonic_ns
        self._wake = wake
        self._credit = credit if credit is not None and credit.enabled else None
        self._on_overflow = on_overflow or "pause"
        #: per-connector counters (reference src/connectors/monitoring.rs);
        #: approximate under concurrent readers — monitoring only
        self.stats = stats if stats is not None else {}
        self.stats.setdefault("rows", 0)
        self.stats.setdefault("retractions", 0)
        self.stats.setdefault("commits", 0)
        self.stats.setdefault("closed", False)

    @property
    def stopped(self) -> bool:
        """True once the scheduler is shutting down; readers should return."""
        return self._stop_event is not None and self._stop_event.is_set()

    def _put(self, kind: str, key: Any, values: Any) -> None:
        seq = None
        if self._credit is not None and kind in ("add", "remove", "batch", "frame"):
            if kind == "batch":
                nrows = len(key)
            elif kind == "frame":
                nrows = _native.load().frame_len(key)
            else:
                nrows = 1
            seq = self._credit.charge(
                self._node_id,
                _approx_event_bytes(kind, key, values),
                nrows,
                self._on_overflow,
                self._stop_event,
                self.stats,
            )
        self._q.put(
            (self._node_id, kind, key, values, self._now_ns(), seq)
        )
        if self._wake is not None:
            self._wake()

    def add(self, key: Pointer, values: tuple) -> None:
        self.stats["rows"] += 1
        self._put("add", key, values)

    def remove(self, key: Pointer, values: tuple) -> None:
        self.stats["retractions"] += 1
        self._put("remove", key, values)

    def add_many(self, rows: list) -> None:
        """Chunked ingest: ``rows`` is a list of (key, values) additions
        delivered as ONE queue item — fast readers (file scan, bulk
        backfill) pay the queue lock per chunk, not per row.  Update
        construction happens here, on the READER thread, overlapping the
        scheduler's epoch work."""
        if rows:
            self.stats["rows"] += len(rows)
            self._put("batch", _build_adds(rows), None)

    def add_frame(self, cap: Any) -> None:
        """Columnar ingest: one native frame (contiguous typed columns +
        interned string pool, lazy row keys) delivered as ONE queue item.
        The frame stays columnar through the drain, routing, and the
        frame-aware operators — no per-row Update objects are built
        unless a downstream operator falls back to the row path."""
        native = _native.load()
        n = native.frame_len(cap)
        if n:
            self.stats["rows"] += n
            self._put("frame", cap, None)

    def commit(self) -> None:
        self.stats["commits"] += 1
        self._put("commit", None, None)

    def close(self) -> None:
        self.stats["closed"] = True
        self._put("close", None, None)


class Scheduler:
    def __init__(
        self,
        graph: EngineGraph,
        *,
        autocommit_ms: int = 50,
        n_workers: int = 1,
        worker_id: int = 0,
    ):
        self.graph = graph
        self.autocommit_ms = autocommit_ms
        self.consumers: dict[int, list[tuple[Node, int]]] = defaultdict(list)
        for node in graph.nodes:
            for port, inp in enumerate(node.inputs):
                self.consumers[inp.id].append((node, port))
        self.ctx = RunContext(n_workers=n_workers, worker_id=worker_id)
        from pathway_tpu.engine.graph import ErrorLogNode

        self._has_error_sink = any(
            isinstance(n, ErrorLogNode) for n in graph.nodes
        )
        self.ctx.error_sink_enabled = self._has_error_sink
        self._stop = threading.Event()
        #: per-stage latency probe (ingest/cut/process/exchange/sink/e2e);
        #: native atomic histograms, surfaced via monitoring + /metrics
        from pathway_tpu.internals.monitoring import LatencyProbe

        self.latency = LatencyProbe()
        #: adaptive micro-batch row budget: cut as soon as this many rows
        #: are buffered, even inside the settle window
        try:
            self._epoch_max_rows = int(
                _os.environ.get("PATHWAY_EPOCH_MAX_ROWS", "32768")
            )
        except ValueError:
            self._epoch_max_rows = 32768
        #: live connector queues (stop() drops a wake sentinel in each)
        self._live_queues: list["queue.Queue"] = []
        #: live cluster while run_cluster is active (exchange probe + hub)
        self._active_cluster: Cluster | None = None
        #: persistence hooks (set by pathway_tpu.persistence.attach_persistence)
        self.persistence: Any = None
        #: epoch-boundary GC sweep hook (set by internals.run._ManagedGc);
        #: called between epochs when transient row data is already dead
        self.gc_tick: Callable[[], None] | None = None
        #: per-worker wall time of the last operator snapshot (rate limit)
        self._last_snapshot_at: dict[int, float] = {}
        #: per-connector counters keyed by input name (monitoring)
        self.connector_stats: dict[str, dict] = {}
        #: guards connector_stats registration + prober snapshotting
        self._prober_lock = threading.Lock()
        #: serializes prober callbacks (they may not be thread-safe).
        #: Separate from _prober_lock so a callback may itself call
        #: snapshot_connector_stats()/snapshot_operator_probes() without
        #: deadlocking; lock order is always cb_lock -> prober_lock.
        self._prober_cb_lock = threading.Lock()
        #: optimizer audit trail (analysis/plan.ExecutionPlan) and its
        #: per-pass rewrite counters — set by internals.run before the
        #: run starts, read by /status + /metrics; None/{} when optimize=0
        self.execution_plan: Any = None
        self.plan_counters: dict[str, int] = {}
        #: restart generation of this process when running under the
        #: cluster supervisor (internals.resilience.ClusterSupervisor sets
        #: PATHWAY_WORKER_RESTARTS; internals.run copies it here) — feeds
        #: the pathway_tpu_worker_restarts_total gauge
        self.worker_restarts = 0
        #: bounded, bytes-accounted connector ingest buffer (backpressure):
        #: readers charge it before enqueueing, the drain loops consume;
        #: PATHWAY_INGEST_BUFFER_BYTES <= 0 disables the accounting
        try:
            cap = int(
                _os.environ.get(
                    "PATHWAY_INGEST_BUFFER_BYTES",
                    str(DEFAULT_INGEST_BUFFER_BYTES),
                )
            )
        except ValueError:
            cap = DEFAULT_INGEST_BUFFER_BYTES
        self.ingest_credit = IngestCredit(cap)
        #: last pressure level pushed to serving (rate-limits the push)
        self._last_pressure_pushed = 0.0

    # ------------------------------------------------------------------
    def snapshot_connector_stats(self) -> dict[str, dict]:
        """Race-free copy of the per-connector counters — the ONLY safe
        way to read them from another thread (dashboard, /metrics,
        probers): registration mutates the registry under the same
        lock."""
        with self._prober_lock:
            return {name: dict(s) for name, s in self.connector_stats.items()}

    def snapshot_operator_probes(self, ctx: Any = None) -> dict[int, dict]:
        """Race-free copy of the per-operator probes (same contract as
        :meth:`snapshot_connector_stats`)."""
        ctx = ctx or self.ctx
        with self._prober_lock:
            return {
                nid: dict(p)
                for nid, p in ctx.stats.get("operators", {}).items()
            }

    def ingest_pressure(self) -> dict[str, Any]:
        """Ingest-buffer pressure snapshot with sources keyed by input
        NAME (monitoring surfaces; node ids are internal).  Shape:
        ``{"totals": {...}, "sources": {name: {rows, bytes, shed_rows,
        shed_bytes, paused}}}``."""
        by_id = self.ingest_credit.snapshot()
        names: dict[int, str] = {}
        for node in self.graph.nodes:
            if isinstance(node, InputNode):
                names[node.id] = getattr(node, "name", str(node.id))
        return {
            "totals": self.ingest_credit.totals(),
            "sources": {
                names.get(nid, str(nid)): snap for nid, snap in by_id.items()
            },
        }

    def pressure_level(self) -> float:
        """Engine pressure in [0, 1]: the max of ingest-buffer occupancy
        and exchange credit backlog — the signal brownout acts on."""
        level = self.ingest_credit.level()
        cluster = self._active_cluster
        if cluster is not None:
            level = max(level, cluster.pressure_level())
        return level

    def _push_serving_pressure(self) -> None:
        """Propagate engine pressure to serving admission (brownout).
        Cheap no-op unless serving is imported; pushes only on material
        change (>= 0.05) or full release so the epoch loop stays hot."""
        import sys

        serving = sys.modules.get("pathway_tpu.serving")
        if serving is None:
            return
        level = self.pressure_level()
        last = self._last_pressure_pushed
        if abs(level - last) < 0.05 and not (level == 0.0 and last > 0.0):
            return
        self._last_pressure_pushed = level
        try:
            serving.push_pressure("engine", level)
        except Exception:
            pass  # monitoring-path best effort; never kill the epoch loop

    def _settle_s(self, last_epoch_s: float) -> float:
        """Adaptive micro-batch settle window (seconds): after the last
        arrival, wait this long for the queue to drain before cutting.
        Scaled to the last epoch's cost (a cheap graph cuts almost
        immediately; an expensive one batches more), floored at 0.5 ms and
        capped at a quarter of the autocommit interval — the interval
        itself remains only the upper bound on hold time."""
        return min(max(last_epoch_s * 0.25, 0.0005), self.autocommit_ms / 4000.0)

    def _replay_speedup(self) -> float:
        """Replay speed factor for REALTIME_REPLAY inter-commit gaps:
        ``PATHWAY_REPLAY_SPEEDUP`` env wins, else the persistence config's
        ``replay_speedup``; values <= 0 mean "as fast as possible"."""
        env = _os.environ.get("PATHWAY_REPLAY_SPEEDUP")
        if env:
            try:
                return float(env)
            except ValueError:
                pass
        cfg = getattr(self.persistence, "config", None)
        try:
            return float(getattr(cfg, "replay_speedup", 1.0))
        except (TypeError, ValueError):
            return 1.0

    def wake(self) -> None:
        """Nudge the streaming loops out of their event waits: notifies
        the cluster hub (parked multi-worker idle branches) and drops a
        ``None`` sentinel into each live connector queue (single-worker
        ``q.get``).  Called by ``stop()`` and the GC pacer."""
        cluster = self._active_cluster
        if cluster is not None:
            cluster.wakeup.notify()
        for q in list(self._live_queues):
            q.put(None)

    def _snapshot_interval(self) -> float:
        """Checkpoint cadence in ms — ONE policy for single-worker and
        cluster paths (they must snapshot at the same cadence).
        Precedence: ``PATHWAY_CHECKPOINT_INTERVAL`` env (seconds), then
        ``Config(checkpoint_interval=)`` (seconds), then the legacy
        ``snapshot_interval_ms``; always floored by the autocommit
        interval (checkpoints ride epoch cuts, which happen no more often
        than that)."""
        cfg = self.persistence.config
        interval_ms = float(getattr(cfg, "snapshot_interval_ms", 0) or 0)
        ci = getattr(cfg, "checkpoint_interval", None)
        env = _os.environ.get("PATHWAY_CHECKPOINT_INTERVAL")
        if env:
            try:
                ci = float(env)
            except ValueError:
                pass
        if ci is not None:
            interval_ms = float(ci) * 1000.0
        return max(interval_ms, self.autocommit_ms)

    def _maybe_snapshot(
        self,
        worker: int,
        epoch: int,
        consumed: dict[int, int],
        wrappers: dict[int, Any],
        ctx: RunContext | None = None,
    ) -> None:
        """Operator snapshot, rate-limited by the checkpoint interval.
        Periodic checkpoints are asynchronous: state pickles here at the
        epoch boundary, disk writes happen off the hot path."""
        interval = self._snapshot_interval()
        now = _time.monotonic()
        if (now - self._last_snapshot_at.get(worker, 0.0)) * 1000.0 < interval:
            return
        self._last_snapshot_at[worker] = now
        self._final_snapshot(
            worker, epoch, consumed, wrappers, ctx=ctx, asynchronous=True
        )

    def _final_snapshot(
        self,
        worker: int,
        epoch: int,
        consumed: dict[int, int],
        wrappers: dict[int, Any],
        ctx: RunContext | None = None,
        asynchronous: bool = False,
    ) -> None:
        """Operator snapshot: force-commit the input logs (so the
        snapshot's consumed counts lie within each log's committed
        prefix), then persist the worker's node states.

        ``asynchronous=True`` (periodic checkpoints): the state pickles on
        THIS thread at the epoch boundary, but the log commits and the
        blob write run on the persistence writer thread — the hot path
        never blocks on disk.  Commit-before-blob ordering is preserved on
        the writer, so a visible snapshot is always consistent with the
        log.  The synchronous path (final snapshot after the finalizing
        flush epoch) drains the async queue FIRST, so the final blob —
        whose state must never re-flush buffered windows on resume — can
        never be overwritten by a stale queued checkpoint."""
        if self.persistence is None or not self.persistence.operator_mode:
            return
        ctx = ctx or self.ctx
        states = self._enriched_states(ctx)
        if asynchronous:
            save_async = getattr(
                self.persistence, "save_operator_snapshot_async", None
            )
            if save_async is not None:
                commit_fns = tuple(
                    fc
                    for wr in wrappers.values()
                    if (fc := getattr(wr, "force_log_commit", None)) is not None
                )
                save_async(worker, epoch, consumed, states, commit_fns)
                return
        flush = getattr(self.persistence, "flush_checkpoints", None)
        if flush is not None:
            flush()
        for w in wrappers.values():
            fc = getattr(w, "force_log_commit", None)
            if fc is not None:
                fc()
        self.persistence.save_operator_snapshot(
            worker, epoch, consumed, states
        )

    def _enriched_states(self, ctx: RunContext) -> dict[int, Any]:
        """Operator states to checkpoint: ``ctx.states`` overlaid with
        every node's :meth:`~pathway_tpu.engine.graph.Node.snapshot_state`
        contribution (external-index serialization rides the same blob,
        keyed to the same connector offsets).  A failing hook degrades to
        the plain state for that node — rebuild-on-replay beats a dead
        checkpoint."""
        states = ctx.states
        extras: dict[int, Any] = {}
        for node in self.graph.nodes:
            try:
                extra = node.snapshot_state(ctx)
            except Exception as e:  # noqa: BLE001
                ctx.log_error(node, f"{node.name}#{node.id} snapshot_state: {e!r}")
                continue
            if extra is not None:
                extras[node.id] = extra
        if not extras:
            return states
        return {**states, **extras}

    def _restore_nodes(self, ctx: RunContext) -> None:
        """Post-restore hook pass: after operator state is restored from a
        snapshot, every node gets ``on_restore(ctx)`` — sinks use it to
        reposition their output files to the checkpointed watermark so
        replayed epochs cannot double-emit.  A failing hook is contained
        like any operator error (degraded output beats a dead run)."""
        for node in self.graph.nodes:
            try:
                node.on_restore(ctx)
            except Exception as e:
                ctx.log_error(node, f"{node.name}#{node.id} on_restore: {e!r}")

    def active_closure(self, root_ids: set[int]) -> set[int]:
        """Node ids reachable from ``root_ids`` or from always-tick nodes —
        the only operators that can see data this epoch.  Every worker
        computes this from the SAME gathered input ids, so collectives for
        globally-idle nodes are skipped in lockstep."""
        roots = set(root_ids)
        for node in self.graph.nodes:
            if node.always_tick:
                roots.add(node.id)
        active = set(roots)
        frontier = list(roots)
        while frontier:
            nid = frontier.pop()
            for consumer, _port in self.consumers.get(nid, ()):
                if consumer.id not in active:
                    active.add(consumer.id)
                    frontier.append(consumer.id)
        return active

    @staticmethod
    def _route_outboxes(route: Any, batch: list, W: int) -> list[list]:
        """Split a batch into per-worker outboxes.  Fast paths: const-zero
        routes copy without any per-row work; routes with a positional
        cell spec split in one native C pass (``route_split``); everything
        else runs the per-row Python closure."""
        if getattr(route, "const_zero", False):
            outboxes: list[list] = [[] for _ in range(W)]
            outboxes[0] = batch
            return outboxes
        positional = getattr(route, "positional", None)
        if isinstance(batch, ColumnarBatch):
            native = _native.load()
            if positional is not None and native is not None:
                try:
                    cbs = [ColumnarBatch() for _ in range(W)]
                    spec = tuple(positional)
                    for seg_kind, seg in batch.segments:
                        if seg_kind == "f":
                            # one native pass: byte-identical destinations
                            # to route_split, children share the pool
                            for dst, sub in enumerate(
                                native.frame_route_split(seg, spec, W)
                            ):
                                cbs[dst].append_frame(sub)
                        else:
                            for dst, sub in enumerate(
                                native.route_split(seg, spec, W)
                            ):
                                if sub:
                                    cbs[dst].extend(sub)
                    return cbs
                except Exception:
                    pass  # fall through to the materialized row path
            batch = batch.to_list()
        if positional is not None:
            native = _native.load()
            if native is not None:
                try:
                    return native.route_split(batch, tuple(positional), W)
                except Exception:
                    pass  # any failure: the per-row path decides row by row
        outboxes = [[] for _ in range(W)]
        for u in batch:
            try:
                dest = route(u) % W
            except Exception:
                dest = 0
            outboxes[dest].append(u)
        return outboxes

    def run_epoch(
        self,
        time: int,
        inject: dict[int, Batch],
        *,
        ctx: RunContext | None = None,
        cluster: Cluster | None = None,
        tid: int = 0,
        active: set[int] | None = None,
    ) -> None:
        ctx = ctx or self.ctx
        ctx.time = time
        from pathway_tpu.engine.graph import set_current_ctx

        set_current_ctx(ctx)  # per-cell errors route to this run's log
        W = cluster.n_workers if cluster is not None else 1
        pending: dict[int, dict[int, list[Update]]] = defaultdict(lambda: defaultdict(list))
        for nid, batch in inject.items():
            pending[nid][0] = (
                batch if isinstance(batch, ColumnarBatch) else list(batch)
            )
        for node in self.graph.nodes:
            if active is not None and node.id not in active:
                continue  # globally idle this epoch: no data can reach it
            ins = pending.pop(node.id, None)
            routes = node.exchange_routes() if W > 1 else None
            if routes is not None:
                # collective: every worker participates even with no local
                # data — rows may arrive from peers
                ins = ins or {}
                n_ports = max(1, len(node.inputs))
                for port in range(n_ports):
                    route = routes[port] if port < len(routes) else None
                    if route is None:
                        continue
                    batch = ins.get(port, ())
                    if not isinstance(batch, (list, ColumnarBatch)):
                        batch = list(batch)
                    outboxes = self._route_outboxes(route, batch, W)
                    ins[port] = cluster.exchange(  # type: ignore[union-attr]
                        ("x", node.id, port, time), tid, outboxes
                    )
            has_input = ins is not None and any(ins.values())
            if not has_input and not node.always_tick and not getattr(ctx, "finalizing", False):
                continue
            n_ports = max(1, len(node.inputs))
            inbatches = [ins.get(i, []) if ins else [] for i in range(n_ports)]
            # columnar/row seam: a frame batch reaching a row-only operator
            # materializes HERE (one place), and every routed row is
            # attributed to its execution path — the
            # pathway_tpu_columnar_rows_total{path} counter that makes a
            # silently degraded pipeline (everything on the fallback path)
            # visible in /metrics and /status
            rows_in = 0
            col_in = 0
            for i, b in enumerate(inbatches):
                if isinstance(b, ColumnarBatch):
                    if node.supports_columnar:
                        col_in += b.frame_rows()
                        rows_in += len(b)
                    else:
                        b = b.to_list()
                        inbatches[i] = b
                        rows_in += len(b)
                else:
                    rows_in += len(b)
            if rows_in:
                cr = ctx.stats.setdefault(
                    "columnar_rows", {"columnar": 0, "row": 0}
                )
                cr["columnar"] += col_in
                cr["row"] += rows_in - col_in
            t0 = _time.perf_counter()
            try:
                out = node.process(ctx, time, inbatches)
            except api.FatalEngineError:
                # unrecoverable by contract (runtime typecheck violations,
                # corrupted state): fail the run, don't contain
                raise
            except Exception as e:
                # per-node containment: a failing operator must not abort
                # the run (reference routes errors to the error log,
                # src/engine/error.rs) — and in cluster mode an uncaught
                # raise would strand peers at the next collective.  The
                # epoch's output for this node is lost, so downstream state
                # may be degraded: log loudly, not just to the error table.
                import logging

                entry = ctx.log_error(node, f"{node.name}#{node.id}: {e!r}")
                msg = str(entry)
                logging.getLogger("pathway_tpu").error(
                    "operator failed (epoch %d dropped for this node): %s",
                    time,
                    msg,
                )
                out = []
            # per-operator probe (reference attach_prober/probe_table,
            # src/engine/graph.rs:988-995): latency + row counts feed the
            # dashboard and the /metrics endpoint
            dt_ms = (_time.perf_counter() - t0) * 1000.0
            probe = ctx.stats.setdefault("operators", {}).get(node.id)
            if probe is None:
                # registration under the lock: monitoring threads copy this
                # dict concurrently (see snapshot_operator_probes)
                with self._prober_lock:
                    probe = ctx.stats["operators"].setdefault(
                        node.id,
                        {
                            "name": f"{node.name}#{node.id}",
                            "kind": type(node).__name__,
                            "rows_in": 0,
                            "rows_out": 0,
                            "total_ms": 0.0,
                            "max_ms": 0.0,
                            "epochs": 0,
                            "state_bytes": 0,
                        },
                    )
            probe["rows_in"] += rows_in
            probe["rows_out"] += len(out)
            probe["total_ms"] += dt_ms
            probe["max_ms"] = max(probe["max_ms"], dt_ms)
            probe["epochs"] += 1
            # measured state bytes, sampled with power-of-two epoch
            # backoff (cost amortizes to O(1) per epoch over a run); the
            # finalizing flush in _finish takes the authoritative sample
            e = probe["epochs"]
            if e & (e - 1) == 0:
                st = ctx.states.get(node.id)
                if st is not None:
                    probe["state_bytes"] = approx_state_bytes(st)
            if out:
                for consumer, port in self.consumers.get(node.id, ()):  # fan-out
                    # extend_batch keeps frame segments columnar through
                    # the fan-out (promoting the pending list if needed)
                    pending[consumer.id][port] = extend_batch(
                        pending[consumer.id][port], out
                    )
        for node in self.graph.nodes:
            node.on_time_end(ctx, time)
        if self.graph.probers:
            # per-WORKER stats, like the reference's ProberStats (each
            # worker probes its own partition; a fleet-wide view is the
            # consumer's aggregation over the "worker" field).  Copied per
            # epoch: the live probe dicts mutate in place, so handing out
            # references would make every stored snapshot show the final
            # cumulative totals.  Connector counters are PROCESS-global,
            # so only thread 0's snapshot carries them (summing across
            # worker snapshots must not multiply them).  The snapshot is
            # built under _prober_lock (registry-iteration safety) but the
            # callbacks run under _prober_cb_lock only, so a prober may
            # itself call snapshot_connector_stats()/snapshot_operator_probes()
            # — the documented "only safe way" to read live stats — without
            # deadlocking on the non-reentrant prober lock.
            with self._prober_cb_lock:
                with self._prober_lock:
                    snapshot = {
                        "time": time,
                        "worker": cluster.worker_index(tid) if cluster else 0,
                        "operators": {
                            nid: dict(p)
                            for nid, p in ctx.stats.get("operators", {}).items()
                        },
                        "connectors": (
                            {
                                name: dict(s)
                                for name, s in self.connector_stats.items()
                            }
                            if tid == 0
                            else {}
                        ),
                    }
                    probers = list(self.graph.probers)
                for cb in probers:
                    try:
                        cb(snapshot)
                    except Exception:  # probers must never break the run
                        import logging

                        logging.getLogger("pathway_tpu").warning(
                            "prober callback failed", exc_info=True
                        )

    def _finish(
        self,
        *,
        ctx: RunContext | None = None,
        cluster: Cluster | None = None,
        tid: int = 0,
        post_epoch: Any = None,
    ) -> None:
        # final flush epoch: frontier advances to +inf; buffering operators release
        ctx = ctx or self.ctx
        ctx.finalizing = True  # type: ignore[attr-defined]
        self.run_epoch(ctx.time + TIME_STEP, {}, ctx=ctx, cluster=cluster, tid=tid)
        # authoritative end-of-run state-bytes sample (the in-epoch
        # sampler backs off exponentially, so its last reading can be
        # half a run old)
        ops = ctx.stats.get("operators", {})
        for nid, st in list(ctx.states.items()):
            probe = ops.get(nid)
            if probe is not None:
                probe["state_bytes"] = approx_state_bytes(st)
        if post_epoch is not None:
            # operator snapshot AFTER the finalizing flush, so restored
            # state never re-flushes buffered windows
            post_epoch()
        for node in self.graph.nodes:
            node.on_end(ctx)

    # ------------------------------------------------------------------
    def run(self) -> RunContext:
        static_inject: dict[int, Batch] = {}
        live_inputs: list[InputNode] = []
        for node in self.graph.nodes:
            if isinstance(node, InputNode):
                if node.static_rows:
                    static_inject[node.id] = _build_adds(node.static_rows)
                if node.subject is not None:
                    live_inputs.append(node)

        if not live_inputs:
            self.run_epoch(0, static_inject)
            self.ctx.time = 0
            self._finish()
            return self.ctx

        # --- streaming mode -------------------------------------------
        t = 0
        # operator snapshot (OPERATOR_PERSISTING): restore compacted node
        # states, skip recomputation; only the committed tail past the
        # snapshot's consumed counts is replayed (bounded replay —
        # reference src/persistence/operator_snapshot.rs)
        snap: dict | None = None
        if self.persistence is not None and self.persistence.operator_mode:
            snap = self.persistence.load_operator_snapshot(0)
        if snap is not None:
            self.ctx.states = snap["states"]
            t = snap["epoch"] + TIME_STEP
            self._restore_nodes(self.ctx)
        elif static_inject:
            # static rows re-inject only when no snapshot holds them already
            self.run_epoch(t, static_inject)
            t += TIME_STEP

        # persistence: replay committed input snapshots as leading epochs
        replayed_counts: dict[int, int] = {}
        consumed: dict[int, int] = dict(snap["consumed"]) if snap else {}
        self.ctx.consumed = consumed  # type: ignore[attr-defined]
        if self.persistence is not None:
            self.persistence.check_topology(1)
            # collect every node's committed epochs FIRST, so replay can
            # interleave sources on the recorded global timeline instead of
            # draining one source's whole span before the next
            pending: list[tuple[float, int, int, list[Update]]] = []
            seq = 0
            for node in live_inputs:
                events = self.persistence.replay_events(node)
                data = [e for e in events if e[0] != "commit"]
                replayed_counts[node.id] = len(data)
                if snap is not None:
                    skip = consumed.get(node.id, 0)
                    tail = data[skip:]
                    if tail:
                        batch = [
                            Update(key, values, 1 if kind == "add" else -1)
                            for kind, key, values in tail
                        ]
                        self.run_epoch(t, {node.id: batch})
                        t += TIME_STEP
                    consumed[node.id] = max(skip, len(data))
                    continue
                consumed[node.id] = len(data)
                epoch: list[Update] = []
                node_wall = float("-inf")  # carry-forward for old records
                for kind, key, values in events:
                    if kind == "add":
                        epoch.append(Update(key, values, 1))
                    elif kind == "remove":
                        epoch.append(Update(key, values, -1))
                    elif kind == "commit":
                        if isinstance(values, float):
                            node_wall = values
                        if epoch:
                            pending.append((node_wall, seq, node.id, epoch))
                            seq += 1
                            epoch = []
            # Legacy commit records (written before wall timestamps were
            # recorded) carry wall == -inf.  Backfill each with the next
            # timestamped wall of the SAME source: those epochs happened
            # before that commit, and the seq tiebreak keeps per-source
            # order, so they interleave just ahead of it instead of all
            # legacy epochs of one source draining before any timestamped
            # epoch of another.  An all-legacy log degenerates to pure
            # arrival (seq) order, which is the pre-timestamp behaviour.
            next_wall: dict[int, float] = {}
            for i in range(len(pending) - 1, -1, -1):
                wall, sq, nid, batch = pending[i]
                if wall == float("-inf") and nid in next_wall:
                    pending[i] = (next_wall[nid], sq, nid, batch)
                elif wall != float("-inf"):
                    next_wall[nid] = wall
            # merge across sources by recorded commit wall clock (stable on
            # ties / legacy records without timestamps)
            pending.sort(key=lambda p: (p[0], p[1]))
            prev_wall: float | None = None
            for wall, _seq, node_id, batch in pending:
                if (
                    self.persistence.realtime_replay
                    and wall != float("-inf")
                ):
                    # REALTIME_REPLAY honours recorded inter-commit gaps
                    # (reference RealtimeReplay); SPEEDRUN and resume run
                    # flat out.  Gaps divide by the replay speed factor
                    # (persistence ``replay_speedup`` / env
                    # PATHWAY_REPLAY_SPEEDUP) and cap at 5 s so a
                    # long-idle recording stays usable; the wait is on
                    # the stop event, so shutdown interrupts it instead
                    # of sleeping through.
                    if prev_wall is not None and wall > prev_wall:
                        speedup = self._replay_speedup()
                        if speedup > 0:
                            self._stop.wait(
                                min((wall - prev_wall) / speedup, 5.0)
                            )
                    prev_wall = wall
                if self._stop.is_set():
                    break
                self.run_epoch(t, {node_id: batch})
                t += TIME_STEP
            if self.persistence.replay_only:
                self.ctx.time = t
                self._finish()
                return self.ctx

        q: "queue.Queue" = queue.Queue()  # lk009: bytes-bounded by IngestCredit.charge
        threads: list[threading.Thread] = []
        wrappers: dict[int, Any] = {}
        for node in live_inputs:
            threads.append(
                self._spawn_supervised(
                    node,
                    node.subject,
                    q,
                    wrappers,
                    replayed_counts.get(node.id, 0),
                    self.ctx,
                )
            )

        # auxiliary inputs (loopbacks) never keep the run alive by
        # themselves: the run ends when all primaries closed AND every
        # auxiliary reports no pending work
        primaries = [n for n in live_inputs if not getattr(n, "auxiliary", False)]
        auxiliaries = [n for n in live_inputs if getattr(n, "auxiliary", False)]
        open_subjects = {n.id for n in primaries}
        buffers: dict[int, list[Update]] = defaultdict(list)
        lat = self.latency
        now_ns = lat.now_ns
        credit = self.ingest_credit
        self._live_queues.append(q)
        autocommit_s = self.autocommit_ms / 1000.0
        commit_requested = False
        rows_buffered = 0
        #: remainder of a batch item split at the epoch row budget; it
        #: re-enters the drain ahead of the queue, preserving source order
        carry: deque = deque()  # lk009: holds at most one split batch item
        #: monotonic instants of the oldest / newest buffered arrival
        first_arrival: float | None = None
        last_arrival = 0.0
        #: earliest enqueue timestamp among buffered events (e2e origin)
        origin_ns: int | None = None
        last_epoch_s = 0.0
        while True:
            # Event-driven wait: ``q.get`` wakes the instant a connector
            # enqueues (or stop() drops its sentinel).  Idle, the
            # autocommit interval is only a defensive heartbeat; with data
            # buffered the wait is the adaptive micro-batch window — cut
            # as soon as the queue drains and settles, at the row budget,
            # or at the autocommit deadline, whichever comes first.
            now = _time.monotonic()
            if first_arrival is not None:
                settle = self._settle_s(last_epoch_s)
                deadline = min(
                    last_arrival + settle, first_arrival + autocommit_s
                )
                timeout = deadline - now
            else:
                timeout = autocommit_s
            item = None
            if carry:
                item = carry.popleft()  # remainder of a budget-split batch
            else:
                try:
                    if timeout > 0.0:
                        item = q.get(timeout=timeout)
                    else:
                        item = q.get_nowait()
                except queue.Empty:
                    pass
            # Greedy drain: pull everything already queued into the buffers
            # in one pass, so epoch size tracks the actual backlog instead
            # of one queue item per loop iteration (an epoch that takes
            # longer than autocommit_ms would otherwise degenerate to one
            # reader chunk per epoch).  A commit item ends the drain — rows
            # enqueued after a commit belong to the next transaction.  The
            # item cap bounds buffer growth and guarantees the cut/stop
            # checks below run even against a producer that enqueues as
            # fast as we drain.
            drained = 0
            data_drained = False
            drain_ns = now_ns()
            while item is not None:
                nid, kind, key, values, enq_ns, seq = item
                if seq is not None and not credit.consume(nid, seq):
                    kind = "shed"  # uncharged by shed_oldest: discard
                if kind == "add":
                    buffers[nid].append(Update(key, values, 1))
                    rows_buffered += 1
                elif kind == "batch":
                    room = self._epoch_max_rows - rows_buffered
                    if 0 < room < len(key):
                        # budget-split: the remainder re-enters the drain
                        # first next pass, preserving per-source order
                        # (already consumed from the credit: seq=None)
                        buffers[nid].extend(key[:room])
                        rows_buffered += room
                        carry.appendleft(
                            (nid, "batch", key[room:], values, enq_ns, None)
                        )
                    else:
                        buffers[nid].extend(key)
                        rows_buffered += len(key)
                elif kind == "frame":
                    native = _native.load()
                    n = native.frame_len(key)
                    room = self._epoch_max_rows - rows_buffered
                    if 0 < room < n:
                        # budget-split: frame_slice shares the string pool
                        # and keeps keys lazy — two column copies, no rows
                        _buffer_frame(
                            buffers, nid, native.frame_slice(key, 0, room)
                        )
                        rows_buffered += room
                        carry.appendleft(
                            (
                                nid,
                                "frame",
                                native.frame_slice(key, room, n),
                                values,
                                enq_ns,
                                None,
                            )
                        )
                    else:
                        _buffer_frame(buffers, nid, key)
                        rows_buffered += n
                elif kind == "remove":
                    buffers[nid].append(Update(key, values, -1))
                    rows_buffered += 1
                elif kind == "commit":
                    commit_requested = True
                    break
                elif kind == "close":
                    open_subjects.discard(nid)
                if kind in ("add", "batch", "remove", "frame"):
                    data_drained = True
                    if enq_ns is not None:
                        lat.record("ingest", drain_ns - enq_ns)
                        if origin_ns is None or enq_ns < origin_ns:
                            origin_ns = enq_ns
                drained += 1
                if drained >= 8192 or rows_buffered >= self._epoch_max_rows:
                    # bounded pass: cut/stop checks must run — the row
                    # budget caps the epoch even when the producer lands
                    # a whole static file in one drain
                    break
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    item = None
            now = _time.monotonic()
            if data_drained:
                last_arrival = now
                if first_arrival is None:
                    first_arrival = now
            have_data = rows_buffered > 0
            if commit_requested and not have_data:
                # an empty commit is a no-op, not a standing order —
                # latched, it would chop the NEXT batch at its first row
                # instead of at that batch's own commit boundary
                commit_requested = False
            settle = self._settle_s(last_epoch_s)
            should_cut = have_data and (
                commit_requested
                or rows_buffered >= self._epoch_max_rows
                or (q.empty() and now - last_arrival >= settle)
                or (
                    first_arrival is not None
                    and now - first_arrival >= autocommit_s
                )
            )
            if should_cut:
                inject = {nid: b for nid, b in buffers.items() if b}
                buffers = defaultdict(list)
                commit_requested = False
                for nid, b in inject.items():
                    consumed[nid] = consumed.get(nid, 0) + len(b)
                cut_ns = now_ns()
                if origin_ns is not None:
                    lat.record("cut", cut_ns - origin_ns)
                # sink/e2e stage anchors for the output nodes of this epoch
                self.ctx.latency = lat
                self.ctx.epoch_origin_ns = origin_ns
                self.ctx.epoch_cut_ns = cut_ns
                ep0 = _time.monotonic()
                _ectx = (
                    epoch_trace_context(int(t / TIME_STEP))
                    if _tracing.enabled()
                    else None
                )
                with _tracing.use(_ectx), _tracing.span(
                    "epoch_process", {"epoch": int(t)}
                ):
                    self.run_epoch(t, inject)
                last_epoch_s = _time.monotonic() - ep0
                self.ctx.epoch_origin_ns = None
                self.ctx.epoch_cut_ns = None
                lat.record("process", int(last_epoch_s * 1e9))
                t += TIME_STEP
                rows_buffered = 0
                first_arrival = None
                origin_ns = None
                if self.gc_tick is not None:
                    self.gc_tick()
                self._push_serving_pressure()
                if (
                    self.persistence is not None
                    and self.persistence.operator_mode
                ):
                    self._maybe_snapshot(0, t - TIME_STEP, consumed, wrappers)
            if not open_subjects and not any(buffers.values()) and not carry:
                # order matters: loopback workers enqueue their result BEFORE
                # decrementing pending, so pending==0 guarantees every result
                # is already visible to the q.empty() check after it
                pending = sum(
                    getattr(n.subject, "pending_count", lambda: 0)()
                    for n in auxiliaries
                )
                if pending == 0 and q.empty():
                    break
            if self._stop.is_set():
                break
        self.ctx.time = t
        self._finish(
            post_epoch=lambda: self._final_snapshot(
                0, self.ctx.time, consumed, wrappers
            )
        )
        return self.ctx

    # ------------------------------------------------------------------
    # multi-worker execution

    def run_cluster(self, cluster: Cluster) -> RunContext:
        """SPMD run over ``cluster.threads`` local workers (this process) in
        a ``cluster.processes``-process mesh.  Returns the worker-0 context
        on process 0 (holds captures/outputs), else this process's first
        worker context."""
        T = cluster.threads
        ctxs = [
            RunContext(
                n_workers=cluster.n_workers, worker_id=cluster.worker_index(tid)
            )
            for tid in range(T)
        ]
        for c in ctxs:
            c.error_sink_enabled = self._has_error_sink
        errors: list[BaseException] = []
        self._active_cluster = cluster  # live exchange probe (monitoring)

        def work(tid: int) -> None:
            try:
                self._worker_loop(cluster, tid, ctxs[tid])
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                errors.append(e)
                cluster.close()  # unblock peers; their collectives now fail

        workers = [
            threading.Thread(target=work, args=(tid,), daemon=True)
            for tid in range(1, T)
        ]
        for w in workers:
            w.start()
        try:
            work(0)
            # bounded joins: a sibling stuck in a collective or a socket
            # call is freed by cluster.close() below — never hang forever
            deadline = _time.monotonic() + 10.0
            for w in workers:
                w.join(max(0.0, deadline - _time.monotonic()))
            if any(w.is_alive() for w in workers):
                cluster.close()  # abort barriers, break sockets
                for w in workers:
                    w.join(2.0)
        except KeyboardInterrupt:
            # ^C: clean teardown instead of a hang — stop the run, break
            # every collective and socket wait, give workers a short
            # grace, then re-raise to the caller
            self._stop.set()
            cluster.close()
            for w in workers:
                w.join(2.0)
            self._active_cluster = None
            raise
        self._active_cluster = None
        if errors:
            raise errors[0]
        # the returned (worker-0) context carries every worker's operator
        # errors: a partitioned operator logs on whichever worker owns the
        # row, and callers read ctx.error_log topology-independently.  The
        # end-of-run allgather covers OTHER PROCESSES too; the thread merge
        # is the fallback when the exchange didn't complete.
        gathered = getattr(ctxs[0], "all_errors", None)
        if gathered is not None:
            ctxs[0].error_log = list(gathered)
        else:
            for c in ctxs[1:]:
                ctxs[0].error_log.extend(c.error_log)
        # exchange-overhead probe: pack/send/unpack/wait totals for this
        # process's collectives, surfaced through monitoring and bench
        ctxs[0].stats["exchange"] = cluster.exchange_stats()
        return ctxs[0]

    def _worker_loop(self, cluster: Cluster, tid: int, ctx: RunContext) -> None:
        W = cluster.n_workers
        w = cluster.worker_index(tid)

        static_inject: dict[int, Batch] = {}
        my_inputs: list[tuple[InputNode, Any]] = []  # (node, subject to run)
        live_node_ids: set[int] = set()
        for node in self.graph.nodes:
            if not isinstance(node, InputNode):
                continue
            if node.static_rows and w == 0:
                static_inject[node.id] = _build_adds(node.static_rows)
            if node.subject is None:
                continue
            live_node_ids.add(node.id)
            part = getattr(node.subject, "partition", None)
            if part is not None:
                sub = part(w, W)
                if sub is not None:
                    my_inputs.append((node, sub))
            elif w == 0:
                my_inputs.append((node, node.subject))

        have_static = any(
            isinstance(n, InputNode) and n.static_rows for n in self.graph.nodes
        )
        t = 0
        if not live_node_ids:
            if have_static:
                self.run_epoch(t, static_inject, ctx=ctx, cluster=cluster, tid=tid)
            ctx.time = 0
            self._finish(ctx=ctx, cluster=cluster, tid=tid)
            return

        # persistence replay (per-worker streams): all workers replay in
        # lockstep — the epoch count is agreed first so collectives align.
        # Static rows inject inside (skipped when a snapshot holds them).
        t, replayed_counts = self._cluster_replay(
            cluster, tid, ctx, my_inputs, t,
            static_inject=static_inject if have_static else None,
        )
        if self.persistence is not None and self.persistence.replay_only:
            # record/replay mode: the snapshot IS the input; starting live
            # readers here would double-count every row
            ctx.time = t
            self._finish(ctx=ctx, cluster=cluster, tid=tid)
            return

        hub = cluster.wakeup
        lat = self.latency
        now_ns = lat.now_ns
        credit = self.ingest_credit
        if tid == 0:
            cluster.latency = lat  # exchange recv waits feed the probe
        q: "queue.Queue" = queue.Queue()  # lk009: bytes-bounded by IngestCredit.charge
        wrappers: dict[int, Any] = {}
        for node, subject in my_inputs:
            self._spawn_supervised(
                node,
                subject,
                q,
                wrappers,
                replayed_counts.get(node.id, 0),
                ctx,
                worker=w,
                wake=hub.notify,
            )

        my_primaries = {
            n.id for n, _s in my_inputs if not getattr(n, "auxiliary", False)
        }
        my_aux = [n for n, _s in my_inputs if getattr(n, "auxiliary", False)]
        open_subjects = set(my_primaries)
        buffers: dict[int, list[Update]] = defaultdict(list)
        round_no = 0
        commit_requested = False
        autocommit_s = self.autocommit_ms / 1000.0
        rows_buffered = 0
        #: remainder of a batch item split at the epoch row budget
        carry: deque = deque()  # lk009: holds at most one split batch item
        first_arrival: float | None = None
        last_arrival = 0.0
        origin_ns: int | None = None
        last_epoch_s = 0.0
        while True:
            # generation snapshot BEFORE the drain: anything enqueued or
            # delivered after this point re-triggers the idle wait below
            # immediately (no lost-wakeup window)
            wake_seen = hub.seq()
            # drain whatever is buffered right now (non-blocking, bounded).
            # A commit item ENDS the drain: rows enqueued after a commit
            # belong to the next transaction — merging across it would
            # consolidate an add with its later retraction into nothing
            # (timed update streams rely on the boundary).
            drained = 0
            data_drained = False
            drain_ns = now_ns()
            while drained < 8192:
                if carry:
                    item = carry.popleft()  # budget-split batch remainder
                else:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                if item is None:
                    continue  # wake sentinel from stop()
                nid, kind, key, values, enq_ns, seq = item
                drained += 1
                if seq is not None and not credit.consume(nid, seq):
                    kind = "shed"  # uncharged by shed_oldest: discard
                if kind == "add":
                    buffers[nid].append(Update(key, values, 1))
                    rows_buffered += 1
                elif kind == "batch":
                    room = self._epoch_max_rows - rows_buffered
                    if 0 < room < len(key):
                        buffers[nid].extend(key[:room])
                        rows_buffered += room
                        carry.appendleft(
                            (nid, "batch", key[room:], values, enq_ns, None)
                        )
                    else:
                        buffers[nid].extend(key)
                        rows_buffered += len(key)
                elif kind == "frame":
                    native = _native.load()
                    n = native.frame_len(key)
                    room = self._epoch_max_rows - rows_buffered
                    if 0 < room < n:
                        _buffer_frame(
                            buffers, nid, native.frame_slice(key, 0, room)
                        )
                        rows_buffered += room
                        carry.appendleft(
                            (
                                nid,
                                "frame",
                                native.frame_slice(key, room, n),
                                values,
                                enq_ns,
                                None,
                            )
                        )
                    else:
                        _buffer_frame(buffers, nid, key)
                        rows_buffered += n
                elif kind == "remove":
                    buffers[nid].append(Update(key, values, -1))
                    rows_buffered += 1
                elif kind == "commit":
                    commit_requested = True
                    break
                elif kind == "close":
                    open_subjects.discard(nid)
                if kind in ("add", "batch", "remove", "frame"):
                    data_drained = True
                    if enq_ns is not None:
                        lat.record("ingest", drain_ns - enq_ns)
                        if origin_ns is None or enq_ns < origin_ns:
                            origin_ns = enq_ns
                if rows_buffered >= self._epoch_max_rows:
                    # row budget reached: stop draining so the epoch cuts
                    # even when a static file lands in one burst
                    break

            aux_pending = sum(
                getattr(n.subject, "pending_count", lambda: 0)() for n in my_aux
            )
            # has_data includes a post-drain queue peek: a loopback enqueues
            # its result BEFORE decrementing pending, so (queue empty AND
            # pending 0) means nothing more can arrive — and since every
            # worker contributes that into the allgather, all workers reach
            # the identical CUT/FINISH/WAIT decision and stay in lockstep
            # the decision below must be a pure function of the gathered
            # statuses so every worker reaches the same CUT/FINISH/WAIT
            # verdict — local clocks only enter via the gathered elapsed
            now = _time.monotonic()
            if data_drained:
                last_arrival = now
                if first_arrival is None:
                    first_arrival = now
            # hold time of the oldest buffered arrival: the autocommit
            # interval bounds how long data may be HELD, not a fixed cut
            # cadence — an idle stretch no longer counts toward it
            elapsed_ms = (
                (now - first_arrival) * 1000.0 if first_arrival is not None else 0.0
            )
            settle_s = self._settle_s(last_epoch_s)
            # adaptive micro-batch vote: this worker's queue drained and
            # settled (or hit the row budget) — gathered below, so ANY
            # worker's vote cuts the epoch cluster-wide
            wants_cut = rows_buffered > 0 and (
                rows_buffered >= self._epoch_max_rows
                or (q.empty() and (now - last_arrival) >= settle_s)
            )
            snap_elapsed_ms = (now - self._last_snapshot_at.get(w, 0.0)) * 1000.0
            status = (
                any(buffers.values()) or bool(carry) or not q.empty(),
                len(open_subjects),
                aux_pending,
                commit_requested,
                self._stop.is_set(),
                elapsed_ms,
                tuple(sorted(nid for nid, b in buffers.items() if b)),
                snap_elapsed_ms,
                wants_cut,
            )
            _tr0 = _time.monotonic()
            # round_statuses, NOT allgather: the per-round consensus rides
            # the pipelined sender streams (piggybacked with data frames),
            # keeping the steady state at ONE synchronization rendezvous
            # per round; allgather stays for O(1) run-boundary agreements
            statuses = cluster.round_statuses(round_no, tid, status)
            if _EPOCH_TRACE:
                import sys as _sys

                _sys.stderr.write(
                    f"[trace w{w}] round {round_no} status gather "
                    f"{(_time.monotonic() - _tr0)*1e3:.1f}ms "
                    f"buf={sum(len(b) for b in buffers.values())} "
                    f"t={_time.monotonic():.3f}\n"
                )
            round_no += 1
            any_data = any(s[0] for s in statuses)
            all_closed = all(s[1] == 0 for s in statuses)
            no_aux = all(s[2] == 0 for s in statuses)
            any_commit = any(s[3] for s in statuses)
            stop = any(s[4] for s in statuses)
            autocommit_due = max(s[5] for s in statuses) >= self.autocommit_ms
            buffered_ids = {nid for s in statuses for nid in s[6]}
            any_wants_cut = any(s[8] for s in statuses)
            # snapshot decision is a pure function of the GATHERED statuses
            # (max elapsed-since-snapshot), so every worker snapshots at the
            # same cut epoch — a per-worker clock decision here would let
            # worker A snapshot at epoch N while B holds N-1, corrupting
            # recovery (rows exchanged in the gap epoch lost or doubled)
            snapshot_due = max(s[7] for s in statuses)
            source_done = all_closed and no_aux
            if buffered_ids and (
                any_commit or any_wants_cut or autocommit_due or source_done or stop
            ):
                inject = {nid: b for nid, b in buffers.items() if b}
                buffers = defaultdict(list)
                commit_requested = False
                consumed = getattr(ctx, "consumed", {})
                for nid, b in inject.items():
                    consumed[nid] = consumed.get(nid, 0) + len(b)
                cut_ns = now_ns()
                if origin_ns is not None:
                    lat.record("cut", cut_ns - origin_ns)
                # sink/e2e anchors for output nodes (ctx is per worker —
                # sinks route to worker 0, which records against its own
                # locally-buffered origin)
                ctx.latency = lat
                ctx.epoch_origin_ns = origin_ns
                ctx.epoch_cut_ns = cut_ns
                ep0 = _time.monotonic()
                # trace: the whole epoch runs under the round's
                # deterministic cross-rank context — exchange / status /
                # checkpoint spans inside stitch into one timeline across
                # every rank (round_no was already advanced past the
                # gather round that cut this epoch)
                _ectx = (
                    epoch_trace_context(round_no - 1)
                    if _tracing.enabled()
                    else None
                )
                # only exchange at operators data can actually reach — the
                # closure is identical on every worker (same gathered ids)
                with _tracing.use(_ectx), _tracing.span(
                    "epoch_process", {"round": round_no - 1, "tid": tid}
                ):
                    self.run_epoch(
                        t, inject, ctx=ctx, cluster=cluster, tid=tid,
                        active=self.active_closure(buffered_ids),
                    )
                last_epoch_s = _time.monotonic() - ep0
                ctx.epoch_origin_ns = None
                ctx.epoch_cut_ns = None
                lat.record("process", int(last_epoch_s * 1e9))
                t += TIME_STEP
                rows_buffered = 0
                first_arrival = None
                origin_ns = None
                if tid == 0 and self.gc_tick is not None:
                    self.gc_tick()  # gc is process-wide: one thread sweeps
                if tid == 0:
                    self._push_serving_pressure()
                if (
                    self.persistence is not None
                    and self.persistence.operator_mode
                ):
                    if snapshot_due >= self._snapshot_interval():
                        # every worker reaches the same verdict (gathered
                        # max), so all checkpoint this same cut epoch — a
                        # globally-consistent coordinated checkpoint.
                        # Async: state pickles here, disk I/O rides the
                        # persistence writer thread off the epoch loop.
                        self._last_snapshot_at[w] = _time.monotonic()
                        with _tracing.span(
                            "checkpoint_write",
                            {"worker": w, "epoch": int(t - TIME_STEP)},
                            ctx=_ectx,
                        ):
                            self._final_snapshot(
                                w, t - TIME_STEP, consumed, wrappers, ctx=ctx,
                                asynchronous=True,
                            )
            elif stop or (source_done and not any_data):
                break
            else:
                # event-driven park (replaces the fixed poll sleep): wait
                # on the cluster hub, woken by a local connector enqueue,
                # a peer frame arrival, any worker entering the next
                # round's collective, the GC pacer, or stop().  With data
                # buffered the wait is bounded by the remaining settle /
                # autocommit-hold window; idle it is bounded by the
                # autocommit interval as a defensive heartbeat only.
                if q.empty() and not carry:
                    now = _time.monotonic()
                    if first_arrival is not None:
                        deadline = min(
                            last_arrival + settle_s,
                            first_arrival + autocommit_s,
                        )
                        wait_s = deadline - now
                    else:
                        wait_s = autocommit_s
                    if wait_s > 0.0:
                        hub.wait(wake_seen, wait_s)
        ctx.time = t
        self._finish(
            ctx=ctx, cluster=cluster, tid=tid,
            post_epoch=lambda: self._final_snapshot(
                w, ctx.time, getattr(ctx, "consumed", {}), wrappers, ctx=ctx
            ),
        )
        # final error-log exchange: errors are logged on whichever worker
        # (possibly another PROCESS) owned the row; gather so the caller's
        # returned context reports them topology-independently.  Best
        # effort — a torn-down cluster must not mask the run result.
        try:
            gathered = cluster.allgather(("errlog", "final"), tid, list(ctx.error_log))
            ctx.all_errors = [e for worker_errs in gathered for e in worker_errs]  # type: ignore[attr-defined]
        except Exception:
            pass

    def _cluster_replay(
        self,
        cluster: Cluster,
        tid: int,
        ctx: RunContext,
        my_inputs: list[tuple[InputNode, Any]],
        t: int,
        static_inject: dict[int, Batch] | None = None,
    ) -> tuple[int, dict[int, int]]:
        """Replay persisted input snapshots in lockstep across workers.
        Returns (next epoch time, data-event count replayed per input).

        With an operator snapshot (OPERATOR_PERSISTING), each worker
        restores its own state shard and replays only its committed tail;
        the starting epoch and replay epoch count are agreed by allgather
        so collectives stay aligned."""
        replayed_counts: dict[int, int] = {}
        epochs_per_input: dict[int, list[Batch]] = {}
        snap: dict | None = None
        if self.persistence is not None:
            w = cluster.worker_index(tid)
            # every worker checks (reads are cheap; the meta write is
            # guarded by "stored is None") so a topology mismatch raises
            # the clear error on ALL processes BEFORE any stream truncation
            self.persistence.check_topology(cluster.n_workers)
            if self.persistence.operator_mode:
                snap = self.persistence.load_operator_snapshot(w)
                # all-or-none AND epoch-consistent: a missing blob (crash
                # between per-worker saves) or epoch skew between workers'
                # snapshots forces full replay everywhere — resuming from
                # mixed cut epochs would lose or double-apply rows
                # exchanged in the gap epochs
                metas = cluster.allgather(
                    ("snap_presence",),
                    tid,
                    (snap is not None, snap["epoch"] if snap is not None else -1),
                )
                if not all(m[0] for m in metas) or len({m[1] for m in metas}) > 1:
                    snap = None
            consumed: dict[int, int] = dict(snap["consumed"]) if snap else {}
            ctx.consumed = consumed  # type: ignore[attr-defined]
            if snap is not None:
                ctx.states = snap["states"]
                self._restore_nodes(ctx)
            for node, _subject in my_inputs:
                events = self.persistence.replay_events(node, worker=w)
                data = [e for e in events if e[0] != "commit"]
                replayed_counts[node.id] = len(data)
                if snap is not None:
                    skip = consumed.get(node.id, 0)
                    tail = data[skip:]
                    consumed[node.id] = max(skip, len(data))
                    if tail:
                        epochs_per_input[node.id] = [
                            [
                                Update(key, values, 1 if kind == "add" else -1)
                                for kind, key, values in tail
                            ]
                        ]
                    continue
                consumed[node.id] = len(data)
                epochs: list[Batch] = []
                cur: list[Update] = []
                for kind, key, values in events:
                    if kind == "add":
                        cur.append(Update(key, values, 1))
                    elif kind == "remove":
                        cur.append(Update(key, values, -1))
                    elif kind == "commit" and cur:
                        epochs.append(cur)
                        cur = []
                if epochs:
                    epochs_per_input[node.id] = epochs
        # agree on the starting epoch (snapshot epochs may differ per
        # worker) and on the replay epoch count — exchange slots are keyed
        # by time, so every worker must walk the same sequence
        my_len = max((len(e) for e in epochs_per_input.values()), default=0)
        my_t0 = (snap["epoch"] + TIME_STEP) if snap is not None else t
        agreed = cluster.allgather(
            ("replay_len",), tid, (my_len, my_t0, snap is not None)
        )
        n_epochs = max(a[0] for a in agreed)
        t = max(max(a[1] for a in agreed), t)
        any_snap = any(a[2] for a in agreed)
        if static_inject is not None and not any_snap:
            # static rows: one collective epoch, injected on worker 0 only
            # (snapshots already contain them, hence the any_snap guard)
            self.run_epoch(t, static_inject, ctx=ctx, cluster=cluster, tid=tid)
            t += TIME_STEP
        for i in range(n_epochs):
            inject = {
                nid: epochs[i]
                for nid, epochs in epochs_per_input.items()
                if i < len(epochs)
            }
            self.run_epoch(t, inject, ctx=ctx, cluster=cluster, tid=tid)
            t += TIME_STEP
        return t, replayed_counts

    def _spawn_supervised(
        self,
        node: InputNode,
        subject: Any,
        q: "queue.Queue",
        wrappers: dict[int, Any],
        replayed: int,
        ctx: Any,
        worker: int = 0,
        wake: Callable[[], None] | None = None,
    ) -> threading.Thread:
        """Start the connector supervisor for one live input.  The reader
        no longer dies permanently on the first exception: the supervisor
        restarts it per ``node.recovery_policy`` (default: the historical
        one-failure-drops-the-source behaviour), building a fresh events
        chain per attempt that resumes past the data events the engine
        already consumed."""
        from pathway_tpu.internals.resilience import ConnectorSupervisor

        with self._prober_lock:
            # counter-key setdefaults inside ConnectorEvents must happen
            # under the lock: a concurrent snapshot's dict(s) copy would
            # otherwise hit a resizing dict
            cstats = self.connector_stats.setdefault(f"{node.name}#{node.id}", {})

        def make_events(resume: int) -> Any:
            with self._prober_lock:
                events: Any = ConnectorEvents(
                    q,
                    node.id,
                    self._stop,
                    stats=cstats,
                    now_ns=self.latency.now_ns,
                    wake=wake,
                    credit=self.ingest_credit,
                    on_overflow=getattr(node, "on_overflow", None),
                )
            if self.persistence is not None:
                events = self.persistence.wrap_events(
                    node, events, resume, worker=worker
                )
                # rebind, so snapshot force-commits hit the LIVE attempt's
                # recording wrapper (key reassignment, never a dict resize)
                wrappers[node.id] = events
            return events

        sup = ConnectorSupervisor(
            node,
            subject,
            make_events,
            getattr(node, "recovery_policy", None),
            ctx=ctx,
            stats=cstats,
            stop_event=self._stop,
            initial_resume=replayed,
            skip_handled_by_events=(
                # the persistence recording wrapper skips the resume
                # prefix itself — but only for nodes it actually wraps
                self.persistence is not None
                and not self.persistence.replay_only
                and not getattr(node, "auxiliary", False)
                and self.persistence.persisted(node)
            ),
            stop_runner=self.stop,
        )
        return sup.start()

    def stop(self) -> None:
        self._stop.set()
        # wake any loop parked in an event wait so shutdown is immediate
        # (q.get / hub.wait would otherwise run out their heartbeat first)
        self.wake()
