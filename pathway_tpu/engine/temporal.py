"""Temporal dataflow operators: behaviors, interval joins, asof joins.

Equivalents of the reference's custom Rust operators:

- :class:`TemporalBehaviorNode` — the forget/buffer/freeze trio of
  ``src/engine/dataflow/operators/time_column.rs`` (750 LoC), driven by
  an **event-time watermark** (max time value seen) instead of timely
  frontiers; same externally observable semantics: rows buffer until
  their release threshold, late rows are frozen out past the cutoff,
  and non-kept rows are retracted when their window expires.
- :class:`IntervalJoinNode` — ``interval_join`` family
  (reference ``stdlib/temporal/_interval_join.py:577``): equi-join plus
  a time-band predicate, with outer-mode unmatched rows.
- :class:`AsofJoinNode` / as-of-now variant — ``asof_join``/``asof_now_join``
  (reference ``_asof_join.py:479``, ``_asof_now_join.py:176``) over the
  ``prev_next``-style sorted neighbour search.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable

from pathway_tpu.engine.graph import EngineGraph, Node
from pathway_tpu.engine.stream import Update, consolidate
from pathway_tpu.internals import api
from pathway_tpu.internals import keys as K
from pathway_tpu.internals.keys import Pointer


def _num(x: Any) -> Any:
    """Times are compared as-is (int/float/datetime all support <)."""
    return x


class TemporalBehaviorNode(Node):
    """Buffer/forget/freeze over an update stream.

    Per row, ``threshold_fn`` gives the release threshold (buffer until
    watermark >= threshold) and ``expiry_fn`` the expiry time (None =
    never).  ``time_fn`` extracts the row's event time, which advances
    the watermark.  Semantics:

    - a row buffers until ``watermark >= threshold`` (buffer);
    - a row arriving with ``expiry <= watermark`` is dropped (freeze);
    - if ``keep_results`` is False, emitted rows are retracted when
      ``watermark >= expiry`` (forget).
    """

    always_tick = True

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        time_fn: Callable[[Pointer, tuple], Any],
        threshold_fn: Callable[[Pointer, tuple], Any] | None,
        expiry_fn: Callable[[Pointer, tuple], Any] | None,
        keep_results: bool = True,
        flush_on_end: bool = True,
        name: str = "temporal_behavior",
    ):
        super().__init__(graph, [input], name)
        self.time_fn = time_fn
        self.threshold_fn = threshold_fn
        self.expiry_fn = expiry_fn
        self.keep_results = keep_results
        self.flush_on_end = flush_on_end

    def make_state(self):
        return {
            "watermark": None,
            # buffered: key -> (values, threshold, expiry)
            "buffered": {},
            # emitted: key -> (values, expiry)
            "emitted": {},
        }

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        out: list[Update] = []
        wm = st["watermark"]
        finalizing = getattr(ctx, "finalizing", False)

        for u in consolidate(inbatches[0]):
            try:
                t = self.time_fn(u.key, u.values)
            except Exception:
                continue
            if t is not None and t is not api.ERROR:
                wm = t if wm is None else max(wm, t)
            if u.diff < 0:
                if u.key in st["buffered"]:
                    del st["buffered"][u.key]
                elif u.key in st["emitted"]:
                    del st["emitted"][u.key]
                    out.append(Update(u.key, u.values, -1))
                continue
            threshold = (
                self.threshold_fn(u.key, u.values)
                if self.threshold_fn is not None
                else None
            )
            expiry = (
                self.expiry_fn(u.key, u.values) if self.expiry_fn is not None else None
            )
            if expiry is not None and wm is not None and expiry <= wm:
                continue  # late: frozen out
            if threshold is None or (wm is not None and threshold <= wm):
                st["emitted"][u.key] = (u.values, expiry)
                out.append(Update(u.key, u.values, 1))
            else:
                st["buffered"][u.key] = (u.values, threshold, expiry)

        # advance watermark: release buffers, expire emitted rows
        if wm is not None:
            st["watermark"] = wm
            release = [
                k
                for k, (_v, thr, _e) in st["buffered"].items()
                if thr <= wm or (finalizing and self.flush_on_end)
            ]
            for k in release:
                # freeze applies at ARRIVAL (late rows); a buffered row was
                # on time, so it always releases — under keep_results=False
                # the expiry sweep below may retract it in the same epoch
                values, _thr, expiry = st["buffered"].pop(k)
                st["emitted"][k] = (values, expiry)
                out.append(Update(k, values, 1))
            if not self.keep_results:
                expired = [
                    k
                    for k, (_v, e) in st["emitted"].items()
                    if e is not None and e <= wm
                ]
                for k in expired:
                    values, _e = st["emitted"].pop(k)
                    out.append(Update(k, values, -1))
        if finalizing and self.flush_on_end:
            for k, (values, _thr, _e) in list(st["buffered"].items()):
                st["emitted"][k] = (values, _e)
                out.append(Update(k, values, 1))
            st["buffered"].clear()
        return consolidate(out)


class IntervalJoinNode(Node):
    """Equi-join + time band: match (l, r) when keys equal and
    ``r.time - l.time in [lower_bound, upper_bound]``."""

    def __init__(
        self,
        graph: EngineGraph,
        left: Node,
        right: Node,
        left_jk_fn: Callable[[Pointer, tuple], tuple],
        right_jk_fn: Callable[[Pointer, tuple], tuple],
        left_time_fn: Callable[[Pointer, tuple], Any],
        right_time_fn: Callable[[Pointer, tuple], Any],
        lower_bound: Any,
        upper_bound: Any,
        left_ncols: int,
        right_ncols: int,
        kind: str = "inner",  # inner|left|right|outer
        name: str = "interval_join",
    ):
        super().__init__(graph, [left, right], name)
        self.left_jk_fn = left_jk_fn
        self.right_jk_fn = right_jk_fn
        self.left_time_fn = left_time_fn
        self.right_time_fn = right_time_fn
        self.lower = lower_bound
        self.upper = upper_bound
        self.left_ncols = left_ncols
        self.right_ncols = right_ncols
        self.kind = kind

    def make_state(self):
        # per side: jk -> {row_key: (values, time)}
        return {"left": {}, "right": {}, "out": {}}

    def _pairs(self, lrows: dict, rrows: dict) -> dict[Pointer, tuple]:
        # rows end with (left_key, right_key) — the JoinResult id protocol
        block: dict[Pointer, tuple] = {}
        lnone = (None,) * self.left_ncols
        rnone = (None,) * self.right_ncols
        lmatched: set = set()
        rmatched: set = set()
        for lk, (lv, lt) in lrows.items():
            for rk, (rv, rt) in rrows.items():
                if lt is None or rt is None:
                    continue
                d = rt - lt
                if self.lower <= d <= self.upper:
                    block[K.join_key(lk, rk)] = lv + rv + (lk, rk)
                    lmatched.add(lk)
                    rmatched.add(rk)
        if self.kind in ("left", "outer"):
            for lk, (lv, _lt) in lrows.items():
                if lk not in lmatched:
                    block[K.join_key(lk, None)] = lv + rnone + (lk, None)
        if self.kind in ("right", "outer"):
            for rk, (rv, _rt) in rrows.items():
                if rk not in rmatched:
                    block[K.ref_scalar("__ij_r__", int(rk))] = lnone + rv + (None, rk)
        return block

    def _apply_side(self, side: dict, batch, jk_fn, time_fn) -> set:
        from pathway_tpu.engine.stream import hashable_row

        dirty = set()
        for u in batch:
            jk = hashable_row(jk_fn(u.key, u.values))
            if jk is None or any(v is None for v in jk):
                continue
            t = time_fn(u.key, u.values)
            rows = side.setdefault(jk, {})
            if u.diff > 0:
                rows[u.key] = (u.values, t)
            else:
                rows.pop(u.key, None)
                if not rows:
                    side.pop(jk, None)
            dirty.add(jk)
        return dirty

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        from pathway_tpu.engine.stream import hashable_row

        dirty: set = set()
        for u in inbatches[0]:
            jk = hashable_row(self.left_jk_fn(u.key, u.values))
            if not (jk is None or any(v is None for v in jk)):
                dirty.add(jk)
        for u in inbatches[1]:
            jk = hashable_row(self.right_jk_fn(u.key, u.values))
            if not (jk is None or any(v is None for v in jk)):
                dirty.add(jk)
        old_blocks = {
            jk: self._pairs(st["left"].get(jk, {}), st["right"].get(jk, {}))
            for jk in dirty
        }
        self._apply_side(st["left"], inbatches[0], self.left_jk_fn, self.left_time_fn)
        self._apply_side(st["right"], inbatches[1], self.right_jk_fn, self.right_time_fn)
        out: list[Update] = []
        for jk in dirty:
            new_block = self._pairs(st["left"].get(jk, {}), st["right"].get(jk, {}))
            old_block = old_blocks[jk]
            for okey, vals in old_block.items():
                if new_block.get(okey) != vals:
                    out.append(Update(okey, vals, -1))
            for okey, vals in new_block.items():
                if old_block.get(okey) != vals:
                    out.append(Update(okey, vals, 1))
        return consolidate(out)


class AsofNowJoinNode(Node):
    """Equi-join answered as-of-now: each left row is matched against the
    right side's state at its arrival epoch and never revised (reference
    ``asof_now_join``, ``stdlib/temporal/_asof_now_join.py:176``)."""

    def __init__(
        self,
        graph: EngineGraph,
        left: Node,
        right: Node,
        left_jk_fn,
        right_jk_fn,
        left_ncols: int,
        right_ncols: int,
        kind: str = "inner",  # inner|left
        name: str = "asof_now_join",
    ):
        super().__init__(graph, [left, right], name)
        self.left_jk_fn = left_jk_fn
        self.right_jk_fn = right_jk_fn
        self.left_ncols = left_ncols
        self.right_ncols = right_ncols
        self.kind = kind

    def make_state(self):
        # right: jk -> {row_key: values}; out: left_key -> [(okey, row)]
        return {"right": {}, "out": {}}

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.stream import hashable_row

        st = ctx.state(self)
        # right side first: a query in the same epoch sees these updates
        for u in consolidate(inbatches[1]):
            jk = hashable_row(self.right_jk_fn(u.key, u.values))
            if jk is None or any(v is None for v in jk):
                continue
            rows = st["right"].setdefault(jk, {})
            if u.diff > 0:
                rows[u.key] = u.values
            else:
                rows.pop(u.key, None)
                if not rows:
                    st["right"].pop(jk, None)
        out: list[Update] = []
        rnone = (None,) * self.right_ncols
        for u in consolidate(inbatches[0]):
            if u.diff > 0:
                jk = hashable_row(self.left_jk_fn(u.key, u.values))
                matches = (
                    st["right"].get(jk, {})
                    if not (jk is None or any(v is None for v in jk))
                    else {}
                )
                emitted = []
                if matches:
                    for rk, rv in matches.items():
                        okey = K.join_key(u.key, rk)
                        row = u.values + rv + (u.key, rk)
                        emitted.append((okey, row))
                elif self.kind == "left":
                    emitted.append(
                        (K.join_key(u.key, None), u.values + rnone + (u.key, None))
                    )
                st["out"][u.key] = emitted
                for okey, row in emitted:
                    out.append(Update(okey, row, 1))
            else:
                for okey, row in st["out"].pop(u.key, ()):  # retract cached
                    out.append(Update(okey, row, -1))
        return consolidate(out)


class AsofJoinNode(Node):
    """For each left row: the closest right row per key by time
    (direction backward: rt <= lt; forward: rt >= lt; nearest: min |d|)."""

    def __init__(
        self,
        graph: EngineGraph,
        left: Node,
        right: Node,
        left_jk_fn,
        right_jk_fn,
        left_time_fn,
        right_time_fn,
        left_ncols: int,
        right_ncols: int,
        direction: str = "backward",  # backward|forward|nearest
        kind: str = "left",  # inner|left
        as_of_now: bool = False,
        name: str = "asof_join",
    ):
        super().__init__(graph, [left, right], name)
        self.left_jk_fn = left_jk_fn
        self.right_jk_fn = right_jk_fn
        self.left_time_fn = left_time_fn
        self.right_time_fn = right_time_fn
        self.left_ncols = left_ncols
        self.right_ncols = right_ncols
        self.direction = direction
        self.kind = kind
        self.as_of_now = as_of_now

    def make_state(self):
        # right: jk -> sorted list of (time, row_key, values)
        # left: jk -> {row_key: (values, time)}
        # out: left_row_key -> emitted values
        return {"right": {}, "left": {}, "out": {}}

    def _match(self, st, jk, lt) -> tuple | None:
        rows = st["right"].get(jk)
        if not rows or lt is None:
            return None
        times = [r[0] for r in rows]
        if self.direction == "backward":
            i = bisect.bisect_right(times, lt) - 1
            return rows[i] if i >= 0 else None
        if self.direction == "forward":
            i = bisect.bisect_left(times, lt)
            return rows[i] if i < len(rows) else None
        # nearest
        i = bisect.bisect_left(times, lt)
        candidates = []
        if i > 0:
            candidates.append(rows[i - 1])
        if i < len(rows):
            candidates.append(rows[i])
        if not candidates:
            return None
        return min(candidates, key=lambda r: abs(r[0] - lt))

    def _result_row(self, st, jk, lkey, lv, lt) -> tuple | None:
        # rows end with (left_key, right_key) — the JoinResult id protocol
        m = self._match(st, jk, lt)
        if m is None:
            if self.kind == "inner":
                return None
            return lv + (None,) * self.right_ncols + (lkey, None)
        return lv + m[2] + (lkey, m[1])

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.stream import hashable_row

        st = ctx.state(self)
        out: list[Update] = []
        dirty_right: set = set()
        for u in consolidate(inbatches[1]):
            jk = hashable_row(self.right_jk_fn(u.key, u.values))
            if jk is None or any(v is None for v in jk):
                continue
            t = self.right_time_fn(u.key, u.values)
            rows = st["right"].setdefault(jk, [])
            entry = (t, u.key, u.values)
            if u.diff > 0:
                bisect.insort(rows, entry, key=lambda r: (r[0], str(r[1])))
            else:
                try:
                    rows.remove(entry)
                except ValueError:
                    pass
            dirty_right.add(jk)

        handled: set = set()
        for u in consolidate(inbatches[0]):
            jk = hashable_row(self.left_jk_fn(u.key, u.values))
            if jk is None or any(v is None for v in jk):
                continue
            handled.add(u.key)
            lt = self.left_time_fn(u.key, u.values)
            if u.diff > 0:
                st["left"].setdefault(jk, {})[u.key] = (u.values, lt)
                row = self._result_row(st, jk, u.key, u.values, lt)
                prev = st["out"].get(u.key)
                if prev is not None and prev != row:
                    out.append(Update(u.key, prev, -1))
                if row is not None and prev != row:
                    out.append(Update(u.key, row, 1))
                    st["out"][u.key] = row
            else:
                st["left"].get(jk, {}).pop(u.key, None)
                prev = st["out"].pop(u.key, None)
                if prev is not None:
                    out.append(Update(u.key, prev, -1))

        if not self.as_of_now:
            for jk in dirty_right:
                for lkey, (lv, lt) in st["left"].get(jk, {}).items():
                    if lkey in handled:
                        continue
                    row = self._result_row(st, jk, lkey, lv, lt)
                    prev = st["out"].get(lkey)
                    if prev == row:
                        continue
                    if prev is not None:
                        out.append(Update(lkey, prev, -1))
                    if row is not None:
                        out.append(Update(lkey, row, 1))
                        st["out"][lkey] = row
                    else:
                        st["out"].pop(lkey, None)
        return consolidate(out)


# multi-worker routing: temporal operators keep watermark/buffer state on a
# single worker, exactly like the reference (TimeKey::shard() -> 1,
# src/engine/dataflow/operators/time_column.rs:44-52)
from pathway_tpu.engine import cluster as _cl

for _cls in (TemporalBehaviorNode, IntervalJoinNode, AsofNowJoinNode, AsofJoinNode):
    _cls.exchange_routes = _cl.route_all_to_zero
