"""Multi-worker execution: worker topology + pipelined collective exchange.

The reference scales out by running the identical dataflow on every worker
and exchanging records so that each stateful operator only keeps the rows
whose shard hash it owns (timely exchange channels: shared memory between
threads, TCP between processes — ``src/engine/dataflow.rs:1068-1072``,
``src/engine/dataflow/config.rs:67-120``).  This module provides the same
capability for the epoch-synchronous engine:

- :class:`Cluster` — ``threads × processes`` workers.  Worker ``w`` lives in
  process ``w // threads``.  Intra-process exchange is shared memory behind
  a barrier; inter-process exchange is a TCP full mesh on
  ``127.0.0.1:first_port+pid`` (reference ``CommunicationConfig::Cluster``).
- ``exchange(slot, outboxes)`` — all-to-all for one (node, port, epoch):
  every worker deposits one outbox per destination worker and receives the
  concatenation of what all workers sent it, merged in global worker order
  (deterministic, so N-worker runs produce the same output as 1-worker).
- ``round_statuses(round_no, obj)`` — the per-round epoch-cut consensus:
  every worker receives the list of all workers' statuses and applies the
  same pure decision function, so no asymmetric coordinator broadcast is
  needed.  This is the ONLY synchronization rendezvous on the steady-state
  path — data exchanges are mailbox waits on the frames themselves.
- ``allgather(slot, obj)`` — small-object gather for O(1) run-boundary
  agreements (replay length, snapshot presence, final error log).

Communication is PIPELINED rather than lock-step (the timely exchange
pusher/puller split, ``external/timely-dataflow/communication/``): a
dedicated sender thread per peer drains an outbound queue and coalesces
everything queued into one writev-style transmission (so an epoch's
per-operator frames and the round's status message share syscalls), and
the per-peer reader threads deserialize frames into slot-keyed mailboxes
as they arrive — serialization, transmission, and deserialization overlap
operator compute instead of bracketing it.  Update payloads travel in the
native binary codec (``pack_updates_into``/``unpack_updates``) appended
straight into a reusable transmission buffer; without the native module
they fall back to pickled plain tuples.

A worker failure is detected in bounded time rather than discovered by an
infinite ``recv``: every sender emits an empty heartbeat transmission when
its link has been idle for ``PATHWAY_CLUSTER_HEARTBEAT_S`` (riding the
existing framing — ``body_len=4, n_msgs=0`` decodes to zero deposits), and
every reader runs its socket with a finite timeout so it can check a
per-peer liveness deadline (``PATHWAY_CLUSTER_LIVENESS_TIMEOUT_S``).

What happens next is the **fail policy** (``fail_policy=`` /
``PATHWAY_CLUSTER_FAIL_POLICY``):

- ``"together"`` (default, the reference semantics — a worker panic
  aborts the cluster, ``dataflow.rs:5533-5536``): a peer silent past the
  deadline — or whose socket dies — fails the whole local mesh.
  ``_fail`` closes every socket so the failure propagates to all peers
  as EOFs within one io tick, and notifies the WakeupHub so parked
  workers observe it immediately.  Recovery is restart-from-persistence
  (``internals/resilience.ClusterSupervisor``).
- ``"isolate"`` (fail-domain isolation, ISSUE 13): membership is
  per-peer.  Every peer carries an ``alive``/``suspect``/``dead`` state
  — half a liveness window of silence marks it *suspect* (observable,
  still served), a full window marks it *dead*.  ``_fail_peer``
  quiesces only the links and exchange routes touching the dead peer:
  its sender stops, its socket closes, its undelivered frames are
  purged from the inbox, and the WakeupHub is notified so nobody blocks
  on it — ``recv_from_all`` then waits only on peers that are still
  alive.  Links are *incarnation-versioned*: the dial handshake carries
  ``(process_id, incarnation)``, a replacement rank rejoins by dialing
  every survivor with a higher incarnation (the persistent accept loop
  admits it, replacing the dead link), and frames from a stale
  incarnation are rejected instead of deposited — a zombie of the old
  rank cannot corrupt the rejoined mesh.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time as _time
from collections import deque
from typing import Any, Callable

from pathway_tpu.engine.columnar import ColumnarBatch, extend_batch
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native_mod
from pathway_tpu.internals import tracing as _tracing

__all__ = [
    "Cluster",
    "WakeupHub",
    "stable_shard",
    "PEER_ALIVE",
    "PEER_SUSPECT",
    "PEER_DEAD",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: per-peer membership states (isolate fail policy).  A peer is *suspect*
#: after half a liveness window of silence — still served, but hedgeable by
#: layers above — and *dead* after a full window or a socket error.
PEER_ALIVE = "alive"
PEER_SUSPECT = "suspect"
PEER_DEAD = "dead"


#: idle-link heartbeat period (seconds); each heartbeat is an empty
#: transmission that refreshes the peer's liveness clock
DEFAULT_HEARTBEAT_S = 1.0
#: a peer silent for this long is declared dead (seconds); must comfortably
#: exceed the heartbeat period so a single delayed frame never false-alarms
DEFAULT_LIVENESS_TIMEOUT_S = 10.0

#: a heartbeat is an EMPTY transmission: body_len=4, n_msgs=0.  The
#: receiver's existing decoder sees zero messages and deposits nothing —
#: the bytes themselves are the signal.
_HEARTBEAT = struct.pack("<QI", 4, 0)

#: default per-peer cap on unacknowledged exchange data bytes
#: (PATHWAY_EXCHANGE_CREDIT_BYTES; <= 0 disables flow control).  A
#: producer with this much data outstanding to one peer waits for a
#: credit grant instead of queueing more — a slow-but-alive peer
#: throttles its upstream instead of growing its mailbox without bound.
DEFAULT_EXCHANGE_CREDIT_BYTES = 64 << 20

#: magic slot for credit grants, piggybacked on ordinary transmissions
#: the way ``round_statuses`` piggybacks trace wires on "#tc": payload is
#: the receiver's cumulative consumed-bytes counter for this link.  The
#: reader intercepts it before the inbox — workers never see the slot.
_CREDIT_SLOT = "#cr"


def _est_boxes_bytes(boxes: list) -> int:
    """Cheap wire-size estimate of an update-box frame at enqueue time
    (exact sizes replace it once the sender thread encodes)."""
    n = 0
    for row in boxes:
        for box in row:
            n += len(box)
    return 96 + 56 * n


def _est_frame_boxes_bytes(boxes: list, native: Any) -> int:
    """Wire-size estimate for columnar boxes: frame segments are priced
    by their actual column-buffer footprint (fixed-width columns make
    this nearly exact), row segments by the per-update constant."""
    n = 96
    for row in boxes:
        for box in row:
            if isinstance(box, ColumnarBatch):
                for kind, seg in box.segments:
                    if kind == "f":
                        n += native.frame_nbytes(seg) + 32
                    else:
                        n += 56 * len(seg)
            else:
                n += 56 * len(box)
    return n


class WakeupHub:
    """Shared wakeup channel for the event-driven scheduler loops.

    Every producer of scheduler-relevant work notifies the hub: connector
    threads on enqueue, the exchange reader threads on frame arrival, any
    worker depositing into a collective (so siblings parked between rounds
    join the next round immediately), the GC pacer, and ``stop()``.  The
    consumer side is a *generation wait*: a worker snapshots ``seq()``
    BEFORE it drains its queues, and later parks in ``wait(seen, ...)`` —
    if anything was produced in between, the generation already moved and
    the wait returns immediately (no lost-wakeup window)."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._seq = 0

    def seq(self) -> int:
        with self._cv:
            return self._seq

    def notify(self) -> None:
        with self._cv:
            self._seq += 1
            self._cv.notify_all()

    def wait(self, seen: int, timeout: float) -> bool:
        """Park until the generation moves past ``seen`` (or timeout, the
        autocommit-bounded heartbeat); True iff a wakeup arrived."""
        with self._cv:
            if self._seq != seen:
                return True
            if timeout > 0.0:
                self._cv.wait(timeout)
            return self._seq != seen


def stable_shard(*values: Any) -> int:
    """Process-stable shard hash of a tuple of cell values (Python's
    builtin ``hash`` is salted per process, so it cannot route rows
    consistently across a TCP cluster; the 128-bit key hash can)."""
    try:
        return int(K.ref_scalar(*values))
    except Exception:
        return int(K.ref_scalar(repr(values)))


# message kinds inside a transmission (see _PeerSender._encode_msg):
#   transmission := [u64 body_len] body
#   body         := [u32 n_msgs] msg*
#   msg          := [u32 slot_len] slot_pickle [u8 kind] payload
_K_OBJ = 0      # [u64 len] pickle — statuses, gathers, control objects
_K_UPDATES = 1  # [u16 n_src][u16 n_dst] ([u64 len] packed_updates)* — binary
_K_PLAIN = 2    # [u64 len] pickle of plain (int_key, values, diff) boxes
#: columnar boxes: [u16 n_src][u16 n_dst], then per box [u16 n_segments]
#: and per segment [u8 tag (0=rows,1=frame)][u64 len][payload] — frame
#: segments ship the zero-copy column buffers (native frame codec) with
#: ONE string pool per transmission (TxPool on encode, the symmetric
#: RxPool on decode: identical insert order, so pool refs resolve by
#: index with no per-slot re-sending of repeated strings)
_K_FRAME = 3


class _PeerSender(threading.Thread):
    """Outbound half of one peer link: drains a queue of (slot, kind,
    payload) messages and ships everything queued at each wake as ONE
    length-prefixed transmission (coalesced framing — an epoch's operator
    frames and the round's status message share a single ``sendall``).
    Serialization happens here, off the worker threads, into a buffer
    whose capacity persists across epochs (no per-epoch allocation churn).
    """

    def __init__(self, peer: int, sock: socket.socket, links: "_ProcessLinks"):
        super().__init__(daemon=True, name=f"pw-cluster-send-{peer}")
        self.peer = peer
        self.sock = sock
        self.links = links
        #: which incarnation of this peer's link the sender serves; a
        #: replaced link's sender dying must not kill the replacement
        self.link_version = 0
        self._q: deque = deque()  # lk009: bounded by exchange credit accounting
        self._cv = threading.Condition()
        # NB: not "_stop" — that shadows threading.Thread._stop(),
        # which join() calls internally on CPython 3.10
        self._stopped = False
        #: close() sets this for a non-ALIVE peer: exit WITHOUT sending
        #: the backlog (bounded teardown must not drain into a stalled
        #: socket — sendall to a suspect peer can block for the full grace)
        self._drop = False
        #: grant nudge from the consuming side (see _ProcessLinks._kick)
        self._kicked = False
        #: estimated bytes of enqueued-but-not-yet-encoded data frames;
        #: part of the producer's outstanding-credit arithmetic
        self.queued_bytes = 0
        self._buf = bytearray()

    def enqueue(
        self, slot: Any, kind: int, payload: Any, est: int = 0
    ) -> None:
        with self._cv:
            self._q.append((slot, kind, payload))
            self.queued_bytes += est
            self._cv.notify()

    def stop(self, drop_backlog: bool = False) -> None:
        with self._cv:
            self._stopped = True
            if drop_backlog:
                self._drop = True
            self._cv.notify()

    def kick(self) -> None:
        """Wake the sender even with an empty mailbox, so a pending
        credit grant ships now instead of riding the next heartbeat."""
        with self._cv:
            self._kicked = True
            self._cv.notify()

    def run(self) -> None:
        links = self.links
        heartbeat_s = links.heartbeat_s
        try:
            while True:
                idle = False
                dropped = -1
                with self._cv:
                    while (
                        not self._q and not self._stopped and not self._kicked
                    ):
                        if not self._cv.wait(heartbeat_s):
                            idle = True
                            break
                    if self._stopped and self._drop:
                        # bounded teardown for a suspect/dead peer: the
                        # backlog is undeliverable — drop it instead of
                        # blocking close() behind a stalled sendall
                        dropped = len(self._q)
                        self._q.clear()
                        self.queued_bytes = 0
                    elif self._q:
                        idle = False
                    elif self._stopped:
                        return  # stopped and drained
                    self._kicked = False
                    items = list(self._q)
                    self._q.clear()
                    self.queued_bytes = 0
                if dropped >= 0:
                    if dropped:
                        with links.stats_lock:
                            links.stats["frames_dropped_on_close"] += dropped
                    return
                # credit grant piggyback: whatever we owe this peer rides
                # the transmission we were about to make anyway
                grant = links._take_grant(self.peer)
                if not items:
                    if grant is not None:
                        # kicked (or idle) with a pending grant: ship it
                        # alone; n_frames=0 keeps the data-transmission
                        # stats invariant (it is liveness+credit, not data)
                        body, _db = self._encode([(_CREDIT_SLOT, _K_OBJ, grant)])
                        self._transmit(body, 0)
                    elif idle:
                        # link idle past the heartbeat period: ship an
                        # empty transmission so the peer's liveness clock
                        # advances
                        self._transmit(_HEARTBEAT, 0)
                    continue
                if grant is not None:
                    items.append((_CREDIT_SLOT, _K_OBJ, grant))
                # thread_time, not perf_counter: wall time in a helper
                # thread mostly measures GIL waits while the workers run;
                # this thread's own CPU is the compute it displaces
                t0 = _time.thread_time()
                t0_ns = _time.monotonic_ns()
                body, data_bytes = self._encode(items)
                t1 = _time.thread_time()
                with links.stats_lock:
                    links.stats["pack_ms"] += (t1 - t0) * 1e3
                _tracing.record_span(
                    "pack", t0_ns, _time.monotonic_ns(),
                    args={"src": links.process_id, "dst": self.peer},
                )
                if data_bytes:
                    # account BEFORE the send: outstanding must never
                    # under-count while bytes are on the wire
                    links._note_data_sent(self.peer, data_bytes)
                self._transmit(body, len(items))
        except Exception as e:  # socket OR encode failure: fail loudly
            links._fail_peer(
                self.peer,
                self.link_version,
                f"send link to process {self.peer} lost: {e!r}",
            )

    def _transmit(self, body: bytes | bytearray, n_frames: int) -> None:
        """Ship one already-encoded transmission (``n_frames == 0`` marks a
        heartbeat).  The single egress point for this link — fault
        injection (``testing/chaos``) patches here to delay or drop frames,
        and a dropped frame mutes heartbeats too, so a muted peer becomes
        *detectably* dead instead of silently lossy."""
        links = self.links
        t0 = _time.thread_time()
        self.sock.sendall(body)
        t1 = _time.thread_time()
        with links.stats_lock:
            st = links.stats
            if n_frames:
                # heartbeats are deliberately NOT "transmissions": that
                # stat means coalesced *data* sendalls, and its invariant
                # frames_sent >= transmissions must survive idle links
                st["transmissions"] += 1
                st["frames_sent"] += n_frames
                st["frames_coalesced"] += n_frames - 1
            else:
                st["heartbeats_sent"] += 1
            st["bytes_sent"] += len(body)
            st["send_ms"] += (t1 - t0) * 1e3

    # ------------------------------------------------------------------
    def _encode(self, items: list) -> tuple[bytearray, int]:
        """Encode one transmission; also returns the wire bytes of the
        DATA (update-box) messages in it — the unit the credit protocol
        accounts in on both sides (the receiver measures the identical
        spans while decoding)."""
        native = _native_mod.load()
        txpool = None
        if native is not None and any(k == _K_FRAME for _s, k, _p in items):
            # one string pool per transmission: frames encoded in msg
            # order, so the receiver's RxPool (same order) resolves pool
            # refs by index — repeated strings cross the wire once
            txpool = native.frame_txpool_new()
        try:
            return self._encode_into(items, native, txpool)
        except Exception:
            if txpool is None:
                raise
            # a frame msg failed mid-encode: the shared pool may hold
            # inserts whose bytes never shipped, so pool refs from later
            # frames would skew on the receiver — rebuild the WHOLE
            # transmission on the row path (no pool, self-contained msgs)
            items = [
                (
                    slot,
                    _K_UPDATES,
                    [
                        [
                            box.to_list()
                            if isinstance(box, ColumnarBatch)
                            else box
                            for box in row
                        ]
                        for row in payload
                    ],
                )
                if kind == _K_FRAME
                else (slot, kind, payload)
                for slot, kind, payload in items
            ]
            return self._encode_into(items, native, None)

    def _encode_into(
        self, items: list, native: Any, txpool: Any
    ) -> tuple[bytearray, int]:
        buf = self._buf
        del buf[:]  # reset length, keep capacity across epochs
        buf += b"\x00" * 12  # u64 body_len + u32 n_msgs, patched below
        data_bytes = 0
        for slot, kind, payload in items:
            before = len(buf)
            self._encode_msg(buf, slot, kind, payload, native, txpool)
            if kind in (_K_UPDATES, _K_FRAME):
                data_bytes += len(buf) - before
        struct.pack_into("<QI", buf, 0, len(buf) - 8, len(items))
        if txpool is not None:
            hits, misses = native.frame_txpool_stats(txpool)
            with self.links.stats_lock:
                st = self.links.stats
                st["strpool_hits"] += hits
                st["strpool_misses"] += misses
        return buf, data_bytes

    @staticmethod
    def _encode_msg(
        buf: bytearray,
        slot: Any,
        kind: int,
        payload: Any,
        native: Any,
        txpool: Any = None,
    ) -> None:
        slot_data = pickle.dumps(slot, protocol=pickle.HIGHEST_PROTOCOL)
        buf += struct.pack("<I", len(slot_data))
        buf += slot_data
        if kind == _K_OBJ:
            data = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            buf += struct.pack("<BQ", _K_OBJ, len(data))
            buf += data
            return
        if kind == _K_FRAME and native is not None:
            # columnar boxes: frame segments append their column buffers
            # verbatim (no per-row boxing), row segments ride the update
            # codec.  A failure here must NOT fall back per-msg — the
            # transmission's shared string pool may already hold inserts
            # from the torn msg — so it propagates and _encode rebuilds
            # the whole transmission on the row path.
            n_src = len(payload)
            n_dst = len(payload[0]) if n_src else 0
            buf += struct.pack("<BHH", _K_FRAME, n_src, n_dst)
            pack_rows = native.pack_updates_into
            pack_frame = native.frame_pack_into
            for row in payload:
                for box in row:
                    segs = (
                        box.segments
                        if isinstance(box, ColumnarBatch)
                        else ([("r", box)] if box else [])
                    )
                    buf += struct.pack("<H", len(segs))
                    for tag, seg in segs:
                        buf += b"\x01" if tag == "f" else b"\x00"
                        at = len(buf)
                        buf += b"\x00" * 8
                        if tag == "f":
                            n = pack_frame(seg, buf, txpool)
                        else:
                            n = pack_rows(seg, buf)
                        struct.pack_into("<Q", buf, at, n)
            return
        if kind == _K_FRAME:
            payload = [
                [
                    box.to_list() if isinstance(box, ColumnarBatch) else box
                    for box in row
                ]
                for row in payload
            ]
        # update boxes: payload[src_tid][dst_tid] is a list of Updates.
        # Binary frames append straight into the transmission buffer (one
        # C++ pass per box, length patched after the fact); a box the
        # codec rejects rolls the whole msg back to the pickled fallback
        # so the peer never sees a torn frame.
        mark = len(buf)
        if native is not None:
            try:
                n_src = len(payload)
                n_dst = len(payload[0]) if n_src else 0
                buf += struct.pack("<BHH", _K_UPDATES, n_src, n_dst)
                pack_into = getattr(native, "pack_updates_into", None)
                for row in payload:
                    for box in row:
                        at = len(buf)
                        buf += b"\x00" * 8
                        if pack_into is not None:
                            n = pack_into(box, buf)
                        else:
                            data = native.pack_updates(box)
                            buf += data
                            n = len(data)
                        struct.pack_into("<Q", buf, at, n)
                return
            except Exception:
                del buf[mark:]
        plain = [
            [[(int(u[0]), u[1], u[2]) for u in box] for box in row]
            for row in payload
        ]
        data = pickle.dumps(plain, protocol=pickle.HIGHEST_PROTOCOL)
        buf += struct.pack("<BQ", _K_PLAIN, len(data))
        buf += data


class _ProcessLinks:
    """TCP full mesh between processes.  Process p listens on
    ``first_port + p``; every pair is connected once (higher pid dials
    lower pid).  Each link runs a sender thread (outbound queue, coalesced
    transmissions) and a reader thread that decodes arriving frames into a
    slot-keyed mailbox — ``recv_from_all`` is a pure mailbox wait."""

    _CONNECT_TIMEOUT_S = 30.0

    def __init__(
        self,
        process_id: int,
        n_processes: int,
        first_port: int,
        hub: "WakeupHub | None" = None,
        heartbeat_s: float | None = None,
        liveness_timeout_s: float | None = None,
        fail_policy: str | None = None,
        incarnation: int | None = None,
    ):
        self.process_id = process_id
        self.n_processes = n_processes
        self._hub = hub
        self.fail_policy = fail_policy or os.environ.get(
            "PATHWAY_CLUSTER_FAIL_POLICY", ""
        ) or "together"
        if self.fail_policy not in ("together", "isolate"):
            raise ValueError(
                f"fail_policy must be 'together' or 'isolate', "
                f"got {self.fail_policy!r}"
            )
        #: this process's incarnation: 0 at first boot, bumped by the
        #: supervisor for each per-rank replacement (the dial handshake
        #: carries it so survivors can tell a rejoin from a zombie)
        self.incarnation = (
            incarnation
            if incarnation is not None
            else _env_int("PATHWAY_CLUSTER_INCARNATION", 0)
        )
        self.heartbeat_s = (
            heartbeat_s
            if heartbeat_s is not None
            else _env_float("PATHWAY_CLUSTER_HEARTBEAT_S", DEFAULT_HEARTBEAT_S)
        )
        self.liveness_timeout_s = (
            liveness_timeout_s
            if liveness_timeout_s is not None
            else _env_float(
                "PATHWAY_CLUSTER_LIVENESS_TIMEOUT_S", DEFAULT_LIVENESS_TIMEOUT_S
            )
        )
        #: finite socket timeout for the reader loops — short enough that
        #: a reader re-checks its peer's liveness deadline several times
        #: per timeout window, long enough to stay off the hot path
        self._io_tick_s = max(0.01, min(1.0, self.liveness_timeout_s / 4.0))
        self._socks: dict[int, socket.socket] = {}
        self._senders: dict[int, _PeerSender] = {}
        self._readers: list[threading.Thread] = []
        self._last_seen: dict[int, float] = {}
        self._inbox: dict[Any, dict[int, Any]] = {}
        #: per-(slot, peer) deposit timestamps (monotonic ns), recorded by
        #: the reader threads and consumed by the collectives to split the
        #: aggregate "status-wait" number into per-peer wait spans
        self._arrival_ns: dict[Any, dict[int, int]] = {}
        self._cv = threading.Condition()
        self._failed: str | None = None
        self._closed = False
        self._running = False  # mesh built: admissions start links inline
        #: membership tables (isolate policy; benign defaults otherwise)
        self._peer_state: dict[int, str] = {}
        self._peer_incarnation: dict[int, int] = {}
        self._dead_reason: dict[int, str] = {}
        #: local link version per peer, bumped each time the peer's socket
        #: is replaced — readers/senders tag themselves with it so frames
        #: and errors from a superseded link are rejected, not believed
        self._link_version: dict[int, int] = {}
        #: per-peer cap on unacknowledged outbound data bytes (credit
        #: flow control); <= 0 disables the producer wait entirely
        self.credit_bytes = _env_int(
            "PATHWAY_EXCHANGE_CREDIT_BYTES", DEFAULT_EXCHANGE_CREDIT_BYTES
        )
        #: credit ledgers, all under _cv.  Outbound: wire data bytes sent
        #: to peer vs. the peer's cumulative consumed-grant.  Inbound:
        #: data bytes we consumed from peer vs. the grant value already
        #: shipped back.  _inbox_bytes mirrors _inbox with wire sizes so
        #: consumption is measured when a worker POPS the payload, not
        #: when the reader deposits it — a slow worker, not a fast
        #: socket, is what must throttle the remote producer.
        self._data_sent: dict[int, int] = {}
        self._data_granted: dict[int, int] = {}
        self._consumed_from: dict[int, int] = {}
        self._granted_sent: dict[int, int] = {}
        self._inbox_bytes: dict[Any, dict[int, int]] = {}
        self.stats: dict[str, Any] = {
            "transmissions": 0,
            "frames_sent": 0,
            "frames_coalesced": 0,
            "heartbeats_sent": 0,
            "bytes_sent": 0,
            "bytes_recv": 0,
            "stale_frames_dropped": 0,
            "peers_declared_dead": 0,
            "peers_rejoined": 0,
            "credit_stalls": 0,
            "credit_stall_ms": 0.0,
            "frames_dropped_on_close": 0,
            "pack_ms": 0.0,
            "send_ms": 0.0,
            "unpack_ms": 0.0,
            # per-transmission string-pool effectiveness of the columnar
            # wire: a hit is a string that crossed as a u32 pool ref
            "strpool_hits": 0,
            "strpool_misses": 0,
        }
        self.stats_lock = threading.Lock()

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", first_port + process_id))
        listener.listen(n_processes)
        self._listener = listener

        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(listener,), daemon=True,
            name=f"pw-cluster-accept-{process_id}",
        )
        self._accept_thread.start()
        if self.incarnation == 0:
            # first boot: dial every lower pid (it is already listening or
            # will be soon); higher pids dial in via the accept loop
            dial_targets = range(process_id)
        else:
            # rejoin (per-rank replacement): every survivor's mesh is
            # already built, so nobody will dial us — dial them ALL, with
            # our incarnation in the handshake so they admit the rejoin
            dial_targets = (
                p for p in range(n_processes) if p != process_id
            )
        for peer in dial_targets:
            self._admit_peer(peer, self._dial(peer, first_port), 0)
        deadline = _time.monotonic() + self._CONNECT_TIMEOUT_S
        with self._cv:
            while len(self._socks) < n_processes - 1:
                left = deadline - _time.monotonic()
                if left <= 0.0:
                    break
                self._cv.wait(min(left, 0.2))
            complete = len(self._socks) == n_processes - 1
        if not complete:
            raise RuntimeError(
                f"process {process_id}: cluster mesh incomplete "
                f"({len(self._socks)}/{n_processes - 1} peers)"
            )
        now = _time.monotonic()
        with self._cv:
            self._running = True
            pairs = list(self._socks.items())
            for peer, _sock in pairs:
                self._last_seen[peer] = now
        for peer, sock in pairs:
            self._start_link(peer, sock)

    def _dial(self, peer: int, first_port: int) -> socket.socket:
        deadline = _time.monotonic() + self._CONNECT_TIMEOUT_S
        while True:
            try:
                sock = socket.create_connection(
                    ("127.0.0.1", first_port + peer), timeout=5.0
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(
                    struct.pack("<II", self.process_id, self.incarnation)
                )
                return sock
            except OSError:
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"process {self.process_id}: cannot reach peer {peer}"
                    )
                _time.sleep(0.05)

    def _accept_loop(self, listener: socket.socket) -> None:
        """Persistent accept loop: admits the initial higher-pid dials AND
        (isolate policy) any later rejoin from a replacement rank — the
        listener stays open for the lifetime of the links."""
        listener.settimeout(1.0)
        while not self._closed:
            try:
                sock, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: teardown
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self._CONNECT_TIMEOUT_S)  # bound handshake
                peer, peer_inc = struct.unpack(
                    "<II", self._recv_exact(sock, 8)
                )
            except (OSError, ConnectionError, struct.error):
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            self._admit_peer(peer, sock, peer_inc)

    def _admit_peer(
        self, peer: int, sock: socket.socket, peer_inc: int
    ) -> None:
        """Record (or replace) the link to ``peer``.  Admission control:
        while a live link stands, a dial with an incarnation <= the known
        one is a duplicate or a zombie of the dead rank — refused.  A
        rejoin (dead peer, or strictly higher incarnation) replaces the
        link: the old socket closes, the old sender stops, the dead
        incarnation's undelivered frames are purged, and — once the mesh
        is running — a fresh sender/reader pair starts immediately."""
        old_sock = old_sender = None
        with self._cv:
            if self._closed:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            known_inc = self._peer_incarnation.get(peer)
            state = self._peer_state.get(peer)
            if (
                peer in self._socks
                and state != PEER_DEAD
                and known_inc is not None
                and peer_inc <= known_inc
            ):
                try:
                    sock.close()
                except OSError:
                    pass
                return
            rejoin = state == PEER_DEAD
            old_sock = self._socks.pop(peer, None)
            old_sender = self._senders.pop(peer, None)
            # quiesce the dead incarnation's routes: its undelivered
            # frames must not satisfy a wait meant for the replacement
            for deposits in self._inbox.values():
                deposits.pop(peer, None)
            self._reset_credit_locked(peer)
            self._link_version[peer] = self._link_version.get(peer, -1) + 1
            self._peer_incarnation[peer] = peer_inc
            self._peer_state[peer] = PEER_ALIVE
            self._dead_reason.pop(peer, None)
            self._socks[peer] = sock
            self._last_seen[peer] = _time.monotonic()
            running = self._running
            self._cv.notify_all()
        if rejoin:
            with self.stats_lock:
                self.stats["peers_rejoined"] += 1
        if old_sender is not None:
            old_sender.stop()
        if old_sock is not None:
            try:
                old_sock.close()
            except OSError:
                pass
        if running:
            self._start_link(peer, sock)
        if self._hub is not None:
            self._hub.notify()

    def _start_link(self, peer: int, sock: socket.socket) -> None:
        version = self._link_version.get(peer, 0)
        sender = _PeerSender(peer, sock, self)
        sender.link_version = version
        self._senders[peer] = sender
        sender.start()
        reader = threading.Thread(
            target=self._read_loop,
            args=(peer, sock, version),
            daemon=True,
            name=f"pw-cluster-recv-{peer}",
        )
        self._readers.append(reader)
        reader.start()

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _recv_live(self, peer: int, sock: socket.socket, view: memoryview) -> None:
        """Exact read that tolerates the finite socket timeout: partial
        progress is kept across timeouts, and each timeout re-checks the
        peer's liveness deadline — a peer silent past it (no data, no
        heartbeats) is declared dead in bounded time."""
        got = 0
        n = len(view)
        while got < n:
            try:
                r = sock.recv_into(view[got:])
            except socket.timeout:
                silent_s = _time.monotonic() - self._last_seen[peer]
                if silent_s > self.liveness_timeout_s:
                    raise ConnectionError(
                        f"peer process {peer} silent for {silent_s:.1f}s "
                        f"(liveness timeout {self.liveness_timeout_s:.1f}s)"
                    ) from None
                if (
                    self.fail_policy == "isolate"
                    and silent_s > self.liveness_timeout_s / 2.0
                    and self._peer_state.get(peer) == PEER_ALIVE
                ):
                    # half a window of silence: observably *suspect* —
                    # layers above may hedge around it before it is dead
                    with self._cv:
                        if self._peer_state.get(peer) == PEER_ALIVE:
                            self._peer_state[peer] = PEER_SUSPECT
                            self._cv.notify_all()
                continue
            if not r:
                raise ConnectionError("peer closed")
            got += r
            self._last_seen[peer] = _time.monotonic()
            if self._peer_state.get(peer) == PEER_SUSPECT:
                with self._cv:
                    if self._peer_state.get(peer) == PEER_SUSPECT:
                        self._peer_state[peer] = PEER_ALIVE
                        self._cv.notify_all()

    def _fail(self, msg: str) -> None:
        with self._cv:
            if self._failed is None:
                self._failed = msg
            self._cv.notify_all()
        # turn a one-sided failure into a whole-mesh one: closing our
        # sockets EOFs every peer's reader within one io tick, so the
        # cluster fails together instead of timing out link by link
        for sock in list(self._socks.values()):
            try:
                sock.close()
            except OSError:
                pass
        if self._hub is not None:
            self._hub.notify()
        # liveness trip: flush the flight recorder while the rings still
        # hold the rounds leading up to the failure (no-op without a
        # spool dir; never raises)
        _tracing.flush("liveness")

    def _fail_peer(self, peer: int, link_version: int, msg: str) -> None:
        """Single-peer failure path.  Under the ``together`` policy this
        is :meth:`_fail` (legacy semantics).  Under ``isolate`` only the
        fail domain of ``peer`` is quiesced: mark it dead, purge its
        undelivered frames, stop its sender, close its socket, and wake
        every waiter — the rest of the mesh keeps running."""
        if self.fail_policy != "isolate":
            self._fail(msg)
            return
        with self._cv:
            if self._closed:
                return
            if self._link_version.get(peer) != link_version:
                return  # a superseded link dying is not news
            if self._peer_state.get(peer) == PEER_DEAD:
                return
            self._peer_state[peer] = PEER_DEAD
            self._dead_reason[peer] = msg
            # quiesce the routes touching this peer: its undelivered
            # frames must never satisfy a later wait
            for deposits in self._inbox.values():
                deposits.pop(peer, None)
            for arrivals in self._arrival_ns.values():
                arrivals.pop(peer, None)
            # release producers parked on this peer's credit: a dead
            # peer's outstanding bytes are void (rejoin restarts at zero)
            self._reset_credit_locked(peer)
            sender = self._senders.pop(peer, None)
            sock = self._socks.pop(peer, None)
            self._cv.notify_all()
        with self.stats_lock:
            self.stats["peers_declared_dead"] += 1
        if sender is not None:
            # the backlog is undeliverable — drop, don't drain
            sender.stop(drop_backlog=True)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._hub is not None:
            self._hub.notify()
        _tracing.flush("liveness")

    def _read_loop(
        self, peer: int, sock: socket.socket, link_version: int = 0
    ) -> None:
        native = _native_mod.load()
        header = bytearray(8)
        header_view = memoryview(header)
        body = bytearray(1 << 16)  # grows to the largest transmission seen
        try:
            # finite timeout: the reader must wake to check the liveness
            # deadline even when the peer sends nothing at all
            sock.settimeout(self._io_tick_s)
            while True:
                self._recv_live(peer, sock, header_view)
                (body_len,) = struct.unpack_from("<Q", header, 0)
                if body_len > len(body):
                    body = bytearray(body_len)
                mv = memoryview(body)[:body_len]
                self._recv_live(peer, sock, mv)
                t0 = _time.thread_time()  # CPU displaced, not GIL waits
                t0_ns = _time.monotonic_ns()
                deposits = self._decode(mv, native)
                dt = (_time.thread_time() - t0) * 1e3
                now_ns = _time.monotonic_ns()
                with self.stats_lock:
                    self.stats["bytes_recv"] += 8 + body_len
                    self.stats["unpack_ms"] += dt
                # credit grants are link-control, not data: apply them
                # (monotonic max — grants are cumulative counters) and
                # keep them out of the inbox
                grant = None
                data = []
                for slot, payload, nbytes in deposits:
                    if slot == _CREDIT_SLOT:
                        if grant is None or payload > grant:
                            grant = payload
                    else:
                        data.append((slot, payload, nbytes))
                if grant is not None:
                    with self._cv:
                        if grant > self._data_granted.get(peer, 0):
                            self._data_granted[peer] = grant
                            # wake producers parked in _wait_for_credit
                            self._cv.notify_all()
                if not data:
                    continue  # heartbeat/grant: bytes already did their job
                _tracing.record_span(
                    "unpack", t0_ns, now_ns,
                    args={"src": peer, "dst": self.process_id},
                )
                with self._cv:
                    if (
                        self._link_version.get(peer, 0) != link_version
                        or self._peer_state.get(peer) == PEER_DEAD
                    ):
                        # generation-versioned rejection: frames from a
                        # superseded or dead incarnation are dropped, not
                        # deposited — a zombie cannot corrupt the mesh
                        with self.stats_lock:
                            self.stats["stale_frames_dropped"] += len(data)
                        return
                    box = self._inbox
                    arrivals = self._arrival_ns
                    for slot, payload, nbytes in data:
                        box.setdefault(slot, {})[peer] = payload
                        arrivals.setdefault(slot, {})[peer] = now_ns
                        if nbytes:
                            self._inbox_bytes.setdefault(slot, {})[
                                peer
                            ] = nbytes
                    self._cv.notify_all()
                if self._hub is not None:
                    # frame arrival is a scheduler-relevant event: wake any
                    # worker parked between rounds so it joins this round
                    self._hub.notify()
        except RuntimeError as e:
            # decode-configuration failure (e.g. native module missing in
            # THIS process): not a peer's fault — fail the whole mesh
            self._fail(str(e))
        except Exception as e:  # socket failure: fail this peer's domain
            self._fail_peer(
                peer, link_version, f"link to process {peer} lost: {e!r}"
            )

    @staticmethod
    def _decode(mv: memoryview, native: Any) -> list:
        """Decode one transmission into [(slot, payload, nbytes)]; update
        payloads come out as fully-built ``Update`` lists (deserialization
        happens here on the reader thread, overlapping worker compute).
        ``nbytes`` is the wire size of DATA messages (update boxes, plain
        or binary) and 0 for control objects — measured over the same
        byte spans the sender charged against the peer's credit, so the
        two ledgers agree exactly."""
        (n_msgs,) = struct.unpack_from("<I", mv, 0)
        off = 4
        out = []
        rxpool = None  # per-transmission, mirrors the sender's TxPool
        for _ in range(n_msgs):
            msg_start = off
            (slot_len,) = struct.unpack_from("<I", mv, off)
            off += 4
            slot = pickle.loads(mv[off : off + slot_len])
            off += slot_len
            kind = mv[off]
            off += 1
            if kind == _K_FRAME:
                if native is None:
                    raise RuntimeError(
                        "cluster exchange: peer sent columnar frames but "
                        "the native module is unavailable in this process"
                    )
                if rxpool is None:
                    rxpool = native.frame_rxpool_new()
                n_src, n_dst = struct.unpack_from("<HH", mv, off)
                off += 4
                boxes = []
                for _s in range(n_src):
                    row = []
                    for _d in range(n_dst):
                        (n_segs,) = struct.unpack_from("<H", mv, off)
                        off += 2
                        parts = []
                        any_frame = False
                        for _g in range(n_segs):
                            tag = mv[off]
                            off += 1
                            (blen,) = struct.unpack_from("<Q", mv, off)
                            off += 8
                            span = mv[off : off + blen]
                            off += blen
                            if tag == 1:
                                any_frame = True
                                parts.append(
                                    ("f", native.frame_unpack(span, rxpool))
                                )
                            else:
                                parts.append(
                                    ("r", native.unpack_updates(span))
                                )
                        if not any_frame:
                            # pure row box: hand workers the plain list
                            # they have always received
                            rows_only: list = (
                                parts[0][1] if len(parts) == 1 else []
                            )
                            if len(parts) > 1:
                                for _t, p in parts:
                                    rows_only.extend(p)
                            row.append(rows_only)
                        else:
                            cb = ColumnarBatch()
                            for t, p in parts:
                                if t == "f":
                                    cb.append_frame(p)
                                else:
                                    cb.extend(p)
                            row.append(cb)
                    boxes.append(row)
                out.append((slot, boxes, off - msg_start))
                continue
            if kind == _K_UPDATES:
                if native is None:
                    # peer packed binary frames we cannot parse (native
                    # load failed only on THIS process, e.g. a corrupted
                    # build cache): fail loudly rather than guess
                    raise RuntimeError(
                        "cluster exchange: peer sent binary frames but "
                        "the native module is unavailable in this process"
                    )
                n_src, n_dst = struct.unpack_from("<HH", mv, off)
                off += 4
                unpack = native.unpack_updates
                boxes = []
                for _s in range(n_src):
                    row = []
                    for _d in range(n_dst):
                        (blen,) = struct.unpack_from("<Q", mv, off)
                        off += 8
                        row.append(unpack(mv[off : off + blen]))
                        off += blen
                    boxes.append(row)
                out.append((slot, boxes, off - msg_start))
                continue
            (dlen,) = struct.unpack_from("<Q", mv, off)
            off += 8
            obj = pickle.loads(mv[off : off + dlen])
            off += dlen
            if kind == _K_PLAIN:
                from pathway_tpu.engine.stream import Update
                from pathway_tpu.internals.keys import Pointer

                obj = [
                    [
                        [Update(Pointer(k), v, d) for k, v, d in box]
                        for box in row
                    ]
                    for row in obj
                ]
            out.append(
                (slot, obj, (off - msg_start) if kind == _K_PLAIN else 0)
            )
        return out

    # ------------------------------------------------------------------
    # credit flow control (exchange data only; control frames are exempt
    # so collectives can never deadlock on a full data window)

    def _reset_credit_locked(self, peer: int) -> None:
        """Void a peer's credit ledgers (link replaced or declared dead);
        caller holds ``_cv`` — its notify_all releases parked producers."""
        self._data_sent.pop(peer, None)
        self._data_granted.pop(peer, None)
        self._consumed_from.pop(peer, None)
        self._granted_sent.pop(peer, None)
        for sizes in self._inbox_bytes.values():
            sizes.pop(peer, None)

    def _note_data_sent(self, peer: int, nbytes: int) -> None:
        with self._cv:
            self._data_sent[peer] = self._data_sent.get(peer, 0) + nbytes

    def _take_grant(self, peer: int) -> int | None:
        """Grant value owed to ``peer`` (our cumulative consumed-bytes
        counter), or None if the last sent grant is still current.  The
        caller (its sender thread) ships it; marking it sent here is safe
        because there is exactly one sender per link."""
        with self._cv:
            consumed = self._consumed_from.get(peer, 0)
            if consumed > self._granted_sent.get(peer, 0):
                self._granted_sent[peer] = consumed
                return consumed
            return None

    def _outstanding_locked(self, peer: int) -> int:
        """Unacknowledged data bytes to ``peer``: encoded-and-sent minus
        granted, plus the mailbox's enqueue-time estimate."""
        sender = self._senders.get(peer)
        queued = sender.queued_bytes if sender is not None else 0
        return (
            self._data_sent.get(peer, 0)
            - self._data_granted.get(peer, 0)
            + queued
        )

    def _wait_for_credit(self, peer: int, est: int) -> None:
        """Producer-side throttle: park until ``est`` more bytes fit in
        the peer's credit window.  Finite wait slices; escapes on grant
        arrival, link failure/close, peer death (isolate quiesces the
        route), or an empty window (one oversized frame always passes —
        the window bounds *accumulation*, not frame size).  This is what
        distinguishes SLOW from DEAD: a slow peer parks us (bounded
        memory), a dead one releases us (frames to it are dropped)."""
        t0_ns = None
        with self._cv:
            while True:
                if self._closed or self._failed is not None:
                    break
                if self._peer_state.get(peer) == PEER_DEAD:
                    break
                if peer not in self._senders:
                    break
                outstanding = self._outstanding_locked(peer)
                if outstanding <= 0 or outstanding + est <= self.credit_bytes:
                    break
                if t0_ns is None:
                    t0_ns = _time.monotonic_ns()
                    with self.stats_lock:
                        self.stats["credit_stalls"] += 1
                self._cv.wait(0.05)
        if t0_ns is not None:
            t1_ns = _time.monotonic_ns()
            with self.stats_lock:
                self.stats["credit_stall_ms"] += (t1_ns - t0_ns) / 1e6
            _tracing.record_span(
                "credit_wait", t0_ns, t1_ns,
                args={"src": self.process_id, "dst": peer, "bytes": est},
            )

    def exchange_pressure(self) -> dict[str, Any]:
        """Per-peer credit backlog snapshot for /metrics + /status."""
        with self._cv:
            peers = {}
            for p in range(self.n_processes):
                if p == self.process_id:
                    continue
                peers[p] = {
                    "backlog_bytes": max(0, self._outstanding_locked(p)),
                    "state": self._peer_state.get(p, PEER_ALIVE),
                }
        with self.stats_lock:
            stalls = self.stats["credit_stalls"]
            stall_ms = self.stats["credit_stall_ms"]
        return {
            "credit_bytes": self.credit_bytes,
            "peers": peers,
            "credit_stalls_total": stalls,
            "credit_stall_ms_total": round(stall_ms, 3),
        }

    def pressure_level(self) -> float:
        """Worst per-peer window occupancy in [0, 1] (0 when disabled)."""
        if self.credit_bytes <= 0:
            return 0.0
        with self._cv:
            worst = 0
            for p in range(self.n_processes):
                if p != self.process_id:
                    worst = max(worst, self._outstanding_locked(p))
        return min(1.0, worst / self.credit_bytes)

    # ------------------------------------------------------------------
    def send_async(self, peer: int, slot: Any, obj: Any) -> None:
        """Queue a pickled-object message; the sender thread coalesces it
        with whatever else is outbound to this peer.  A frame addressed
        to a dead peer (isolate policy) is dropped — its route is
        quiesced, and the rejoin handshake re-opens it.  Control objects
        are credit-exempt: statuses, gathers, and barriers must flow even
        with the data window full, or the mesh would deadlock."""
        sender = self._senders.get(peer)
        if sender is not None:
            sender.enqueue(slot, _K_OBJ, obj)

    def send_updates_async(self, peer: int, slot: Any, boxes: list) -> None:
        """Queue an update-box frame (``boxes[src_tid][dst_tid]`` lists of
        Updates); serialization happens on the sender thread.  With credit
        flow control on, first waits for window room — backpressure
        propagates to the calling worker, which stops cutting epochs,
        which fills the ingest buffer, which pauses the readers."""
        est = _est_boxes_bytes(boxes)
        if self.credit_bytes > 0:
            self._wait_for_credit(peer, est)
        sender = self._senders.get(peer)
        if sender is not None:
            sender.enqueue(slot, _K_UPDATES, boxes, est=est)

    def send_frames_async(self, peer: int, slot: Any, boxes: list) -> None:
        """Queue a columnar-box frame (``boxes[src_tid][dst_tid]`` lists
        of Updates OR :class:`ColumnarBatch`); frame segments are packed
        zero-copy on the sender thread.  Same credit discipline as
        ``send_updates_async`` — columnar data is still data."""
        native = _native_mod.load()
        if native is None:
            # no native codec, so no frames exist to preserve anyway
            return self.send_updates_async(peer, slot, boxes)
        est = _est_frame_boxes_bytes(boxes, native)
        if self.credit_bytes > 0:
            self._wait_for_credit(peer, est)
        sender = self._senders.get(peer)
        if sender is not None:
            sender.enqueue(slot, _K_FRAME, boxes, est=est)

    def recv_from_all(self, slot: Any) -> dict[int, Any]:
        """Block until every *live* peer delivered a payload for ``slot``.

        A notified wait: the reader threads ``notify_all`` on every
        deposit, ``_fail`` notifies on link loss, and ``_fail_peer``
        notifies on a single-peer death (so nobody blocks on a dead
        peer).  Under the ``together`` policy the live set is all peers
        and any failure raises; under ``isolate`` dead peers are simply
        absent from the returned dict — degraded, not dead.  The wait
        timeout is defense-in-depth only (failure detection lives in the
        readers' liveness deadlines)."""
        with self._cv:
            while True:
                if self._failed is not None:
                    raise RuntimeError(f"cluster failure: {self._failed}")
                got = self._inbox.get(slot)
                out = None
                if self.fail_policy == "isolate":
                    live = [
                        p
                        for p in range(self.n_processes)
                        if p != self.process_id
                        and self._peer_state.get(p) != PEER_DEAD
                    ]
                    have = got if got is not None else {}
                    if all(p in have for p in live):
                        out = {p: have.pop(p) for p in live}
                        if not have:
                            self._inbox.pop(slot, None)
                elif got is not None and len(got) == self.n_processes - 1:
                    out = self._inbox.pop(slot)
                if out is not None:
                    kick = self._consume_slot_locked(slot, out)
                    break
                self._cv.wait(1.0)
        for p in kick:
            sender = self._senders.get(p)
            if sender is not None:
                sender.kick()
        return out

    def _consume_slot_locked(self, slot: Any, out: dict[int, Any]) -> list:
        """Account a satisfied slot's wire bytes as CONSUMED (this is the
        moment a worker actually took delivery); returns the peers whose
        pending grant grew large enough to ship eagerly rather than ride
        the next round's piggyback."""
        kick = []
        sizes = self._inbox_bytes.get(slot)
        if sizes is None:
            return kick
        eager = self.credit_bytes // 8 if self.credit_bytes > 0 else None
        for p in out:
            nb = sizes.pop(p, 0)
            if not nb:
                continue
            consumed = self._consumed_from.get(p, 0) + nb
            self._consumed_from[p] = consumed
            if (
                eager is not None
                and consumed - self._granted_sent.get(p, 0) >= eager
            ):
                kick.append(p)
        if not sizes:
            self._inbox_bytes.pop(slot, None)
        return kick

    def pop_arrivals(self, slot: Any) -> dict[int, int]:
        """Consume the per-peer deposit timestamps (monotonic ns) the
        reader threads recorded for ``slot`` — the collectives turn these
        into per-peer wait spans after the slot is satisfied."""
        with self._cv:
            return self._arrival_ns.pop(slot, {})

    # ------------------------------------------------------------------
    def peer_states(self) -> dict[int, str]:
        """Membership snapshot: peer pid -> ``alive``/``suspect``/``dead``
        (peers never heard from report ``alive`` — absence of evidence is
        not failure under the liveness deadline)."""
        with self._cv:
            return {
                p: self._peer_state.get(p, PEER_ALIVE)
                for p in range(self.n_processes)
                if p != self.process_id
            }

    def dead_peers(self) -> list[int]:
        with self._cv:
            return sorted(
                p
                for p, s in self._peer_state.items()
                if s == PEER_DEAD
            )

    def membership(self) -> dict[int, dict[str, Any]]:
        """Full membership view: per peer ``state``, last advertised
        ``incarnation``, and the death ``reason`` (if dead)."""
        with self._cv:
            return {
                p: {
                    "state": self._peer_state.get(p, PEER_ALIVE),
                    "incarnation": self._peer_incarnation.get(p, 0),
                    "reason": self._dead_reason.get(p),
                }
                for p in range(self.n_processes)
                if p != self.process_id
            }

    def close(self) -> None:
        """Bounded teardown: ask the senders to drain, give them a short
        grace, then close the sockets (which breaks any sender stuck in
        ``sendall`` and any reader parked in ``recv``) and re-join — no
        unbounded join anywhere, so teardown cannot hang."""
        with self._cv:
            self._closed = True
            states = dict(self._peer_state)
            self._cv.notify_all()  # release producers in _wait_for_credit
        senders = list(self._senders.values())
        for sender in senders:
            # a suspect/dead peer's backlog is undeliverable and its
            # socket may be stalled: DROP it — draining would park the
            # sender in sendall for the whole teardown grace
            sender.stop(
                drop_backlog=states.get(sender.peer, PEER_ALIVE) != PEER_ALIVE
            )
        for sender in senders:
            sender.join(0.5)
        for sock in list(self._socks.values()):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        for sender in senders:
            sender.join(1.5)
        for reader in self._readers:
            reader.join(1.5)


class Cluster:
    """Worker topology + collectives for ``threads × processes`` workers.

    Worker global index = ``process_id * threads + thread_id``.  Exchange
    within a process is shared memory; across processes frames travel on
    per-peer sender threads and coalesce into one transmission per peer
    per drain (usually one per epoch round on the steady-state path).
    """

    def __init__(
        self,
        *,
        threads: int = 1,
        processes: int = 1,
        process_id: int = 0,
        first_port: int = 10000,
        heartbeat_s: float | None = None,
        liveness_timeout_s: float | None = None,
        fail_policy: str | None = None,
        incarnation: int | None = None,
    ):
        self.threads = threads
        self.processes = processes
        self.process_id = process_id
        self.n_workers = threads * processes
        #: shared wakeup channel: connector enqueues, frame arrivals,
        #: collective deposits, the gc pacer and stop() all notify it;
        #: the scheduler's idle branch parks on it instead of sleeping
        self.wakeup = WakeupHub()
        #: per-stage latency probe (set by the scheduler); exchange recv
        #: waits are recorded here when present
        self.latency: Any = None
        self._links = (
            _ProcessLinks(
                process_id,
                processes,
                first_port,
                hub=self.wakeup,
                heartbeat_s=heartbeat_s,
                liveness_timeout_s=liveness_timeout_s,
                fail_policy=fail_policy,
                incarnation=incarnation,
            )
            if processes > 1
            else None
        )
        self._barrier = threading.Barrier(threads)
        self._local: dict[Any, Any] = {}  # slot -> per-tid deposits
        self._merged: dict[Any, Any] = {}  # slot -> per-tid results
        self._lock = threading.Lock()
        #: collective-level counters (thread 0 only mutates, so no lock);
        #: transport counters live on the links — exchange_stats() merges
        self._stats: dict[str, Any] = {
            "exchange_calls": 0,
            "allgather_calls": 0,
            "status_rounds": 0,
            "recv_wait_ms": 0.0,
            "allgather_wait_ms": 0.0,
            "status_wait_ms": 0.0,
            # the aggregate status_wait_ms split by the peer whose frame
            # arrived at that offset into the wait — the trace records the
            # same split as per-round "status_wait_peer" spans
            "status_wait_by_peer_ms": {},
        }
        #: last epoch trace context received via the round-status
        #: piggyback from rank 0 (None until the first piggybacked round;
        #: tests assert genuine cross-rank propagation through this)
        self.last_epoch_wire: Any = None
        if processes > 1:
            _tracing.set_rank(process_id)

    def worker_index(self, thread_id: int) -> int:
        return self.process_id * self.threads + thread_id

    def peer_states(self) -> dict[int, str]:
        """Membership snapshot (``{}`` for a single-process cluster)."""
        return {} if self._links is None else self._links.peer_states()

    def membership(self) -> dict[int, dict[str, Any]]:
        return {} if self._links is None else self._links.membership()

    def exchange_pressure(self) -> dict[str, Any]:
        """Per-peer credit backlog (``{}`` for a single-process cluster)."""
        return {} if self._links is None else self._links.exchange_pressure()

    def pressure_level(self) -> float:
        """Worst peer credit-window occupancy in [0, 1]."""
        return 0.0 if self._links is None else self._links.pressure_level()

    def exchange_stats(self) -> dict[str, Any]:
        """Snapshot of the exchange-overhead probe: collective counts and
        wait times plus transport pack/send/unpack times and volumes."""
        st = dict(self._stats)
        st["status_wait_by_peer_ms"] = dict(st["status_wait_by_peer_ms"])
        if self._links is not None:
            with self._links.stats_lock:
                st.update(self._links.stats)
        return st

    # ------------------------------------------------------------------
    def exchange(
        self, slot: Any, thread_id: int, outboxes: list[list]
    ) -> list:
        """All-to-all: ``outboxes[w]`` holds this worker's updates destined
        to global worker ``w``; returns the merged inbox for this worker,
        concatenated in global source-worker order.

        Outbound frames are queued to the per-peer sender threads (which
        pack them in the native binary codec and coalesce them with any
        other outbound traffic); the wait below is a mailbox wait on the
        peers' DATA — the reader threads have already deserialized it.
        """
        T, P = self.threads, self.processes
        # exchange stage = this worker's whole all-to-all (barrier sync +
        # mailbox recv + merge); recorded once per collective on thread 0
        lat = self.latency if thread_id == 0 else None
        t_x0 = _time.perf_counter() if lat is not None else 0.0
        t_x0_ns = _time.monotonic_ns() if thread_id == 0 else 0
        with self._lock:
            self._local.setdefault(slot, {})[thread_id] = outboxes
        self._barrier.wait()
        if thread_id == 0:
            st = self._stats
            st["exchange_calls"] += 1
            local = self._local.pop(slot)
            if self._links is not None:
                for peer in range(P):
                    if peer == self.process_id:
                        continue
                    boxes = [
                        [
                            local[src_tid][peer * T + dst_tid]
                            for dst_tid in range(T)
                        ]
                        for src_tid in range(T)
                    ]
                    if any(
                        isinstance(b, ColumnarBatch)
                        for row in boxes
                        for b in row
                    ):
                        self._links.send_frames_async(peer, slot, boxes)
                    else:
                        self._links.send_updates_async(peer, slot, boxes)
                t0 = _time.perf_counter()
                t0_ns = _time.monotonic_ns()
                remote = self._links.recv_from_all(slot)
                wait_s = _time.perf_counter() - t0
                st["recv_wait_ms"] += wait_s * 1e3
                # per-peer recv spans: each peer's frame arrival stamps how
                # long THIS rank's exchange waited on THAT rank — the span
                # names both sides (src = sender, dst = this rank)
                arrivals = self._links.pop_arrivals(slot)
                if _tracing.enabled():
                    for peer, arr_ns in arrivals.items():
                        _tracing.record_span(
                            "exchange_recv", t0_ns, max(arr_ns, t0_ns),
                            args={"src": peer, "dst": self.process_id},
                        )
            else:
                remote = {}
            merged: list[list] = [[] for _ in range(T)]
            base = self.process_id * T
            for src_pid in range(P):
                if src_pid == self.process_id:
                    for src_tid in range(T):
                        boxes = local[src_tid]
                        for dst_tid in range(T):
                            merged[dst_tid] = extend_batch(
                                merged[dst_tid], boxes[base + dst_tid]
                            )
                else:
                    rows = remote.get(src_pid)  # decoded by the reader
                    if rows is None:
                        continue  # peer dead (isolate): degraded merge
                    for src_tid in range(T):
                        row = rows[src_tid]
                        for dst_tid in range(T):
                            merged[dst_tid] = extend_batch(
                                merged[dst_tid], row[dst_tid]
                            )
            with self._lock:
                self._merged[slot] = merged
        self._barrier.wait()
        with self._lock:
            merged = self._merged[slot]
            result = merged[thread_id]
            merged[thread_id] = None  # type: ignore[call-overload]
            if all(m is None for m in merged):
                self._merged.pop(slot, None)
        if lat is not None:
            lat.record("exchange", int((_time.perf_counter() - t_x0) * 1e9))
        if thread_id == 0:
            _tracing.record_span(
                "exchange", t_x0_ns, _time.monotonic_ns(),
                args={"rank": self.process_id},
            )
        return result

    # ------------------------------------------------------------------
    def _gather(
        self, slot: Any, thread_id: int, obj: Any, calls_key: str, wait_key: str
    ) -> list:
        """Shared gather: every worker contributes one object; every worker
        receives the list of all objects in global worker order."""
        T, P = self.threads, self.processes
        with self._lock:
            self._local.setdefault(slot, {})[thread_id] = obj
        # a worker entering a collective is itself a wakeup: siblings
        # parked in the scheduler's idle branch must join this round
        self.wakeup.notify()
        self._barrier.wait()
        if thread_id == 0:
            st = self._stats
            st[calls_key] += 1
            local = self._local.pop(slot)
            if self._links is not None:
                payload = [local[tid] for tid in range(T)]
                for peer in range(P):
                    if peer != self.process_id:
                        self._links.send_async(peer, slot, payload)
                t0 = _time.perf_counter()
                t0_ns = _time.monotonic_ns()
                remote = self._links.recv_from_all(slot)
                st[wait_key] += (_time.perf_counter() - t0) * 1e3
                # satellite: split the opaque wait by WHICH peer held it —
                # each peer's deposit timestamp bounds this rank's wait on
                # that peer; status rounds additionally emit per-peer spans
                # so a slow rank is attributable to specific rounds
                arrivals = self._links.pop_arrivals(slot)
                if wait_key == "status_wait_ms":
                    by_peer = st["status_wait_by_peer_ms"]
                    round_no = slot[1] if isinstance(slot, tuple) else None
                    ctx = (
                        epoch_trace_context(round_no)
                        if round_no is not None and _tracing.enabled()
                        else None
                    )
                    for peer, arr_ns in arrivals.items():
                        waited_ns = max(arr_ns - t0_ns, 0)
                        by_peer[peer] = (
                            by_peer.get(peer, 0.0) + waited_ns / 1e6
                        )
                        if ctx is not None:
                            _tracing.record_span(
                                "status_wait_peer", t0_ns,
                                t0_ns + waited_ns, ctx=ctx,
                                args={
                                    "src": peer,
                                    "dst": self.process_id,
                                    "round": round_no,
                                },
                            )
            else:
                remote = {}
            gathered: list = []
            for src_pid in range(P):
                if src_pid == self.process_id:
                    gathered.extend(local[tid] for tid in range(T))
                else:
                    part = remote.get(src_pid)
                    if part is not None:  # dead peer (isolate): absent
                        gathered.extend(part)
            with self._lock:
                self._merged[slot] = gathered
        self._barrier.wait()
        with self._lock:
            gathered = self._merged[slot]
            # every thread reads the same list; last reader cleans up
            counter = self._local.setdefault(("__done__", slot), {"n": 0})
            counter["n"] += 1
            if counter["n"] == T:
                self._merged.pop(slot, None)
                self._local.pop(("__done__", slot), None)
        return gathered

    def allgather(self, slot: Any, thread_id: int, obj: Any) -> list:
        """Run-boundary gather (replay length, snapshot presence, final
        error log): O(1) calls per run.  The per-round epoch-cut gather is
        :meth:`round_statuses` — keeping them distinct keeps the steady
        state at exactly one synchronization rendezvous per round."""
        return self._gather(
            slot, thread_id, obj, "allgather_calls", "allgather_wait_ms"
        )

    def round_statuses(self, round_no: int, thread_id: int, status: Any) -> list:
        """Epoch-cut consensus for one scheduler round: gathers every
        worker's status tuple.  The status message rides the same framed
        stream as data — the sender thread coalesces it with any operator
        frames still outbound (piggybacked consensus), and an idle round
        sends it as a lone tiny transmission (the empty-frame fallback).

        Trace piggyback: rank 0's thread 0 rides its epoch trace context
        on its status contribution — every rank derives the same context
        deterministically (:func:`epoch_trace_context`), so this is the
        *confirmation* channel that stitches cross-rank spans: receivers
        remember the last wire context (``last_epoch_wire``), and the
        wrapper is stripped before the statuses reach the scheduler (its
        ``s[0..8]`` indexing never sees it)."""
        tracing_on = _tracing.enabled()
        if tracing_on and thread_id == 0 and self.process_id == 0:
            status = (
                "#tc", epoch_trace_context(round_no).to_wire(), status
            )
        gathered = self._gather(
            ("s", round_no), thread_id, status, "status_rounds", "status_wait_ms"
        )
        # unwrap unconditionally: rank 0 may have tracing on while this
        # rank has it off, and the scheduler must never see the wrapper
        out = []
        for s in gathered:
            if isinstance(s, tuple) and len(s) == 3 and s[0] == "#tc":
                self.last_epoch_wire = s[1]
                out.append(s[2])
            else:
                out.append(s)
        return out

    def close(self) -> None:
        self._barrier.abort()  # free local threads blocked in a collective
        self.wakeup.notify()  # free threads parked in the idle branch
        if self._links is not None:
            self._links.close()


def epoch_trace_context(round_no: int) -> "_tracing.TraceContext":
    """The deterministic trace context for one cluster round: every rank
    derives the identical trace id from the round number alone (FNV-1a —
    NOT the builtin ``hash``, which is salted per process), so spans
    recorded on different ranks stitch under one trace without waiting
    for the piggybacked context to arrive.  The rank-0 context riding the
    round-status frames (:meth:`Cluster.round_statuses`) then confirms
    the stitch — and is what tests assert genuine propagation on."""
    h = 0xCBF29CE484222325
    for b in b"epoch:%d" % round_no:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    h = h or 1
    return _tracing.TraceContext(h, h, True)


def route_by_key(u: Any) -> int:
    """Default co-location: the row key (already a 128-bit stable hash)."""
    return int(u.key)


#: native route_split spec: empty tuple = key-value routing (see
#: native/pathway_native.cpp py_route_split)
route_by_key.positional = ()  # type: ignore[attr-defined]


def route_to_zero(_u: Any) -> int:
    """Centralized operators (temporal buffers, external indexes, outputs):
    the reference shards these to a single worker too
    (``TimeKey::shard() -> 1``, ``src/engine/dataflow/operators/time_column.rs:44-52``)."""
    return 0


#: scheduler fast path: everything to worker 0 without a per-row call
route_to_zero.const_zero = True  # type: ignore[attr-defined]


def route_all_to_zero(node: Any) -> list:
    """``exchange_routes`` implementation for centralized operators: one
    ``route_to_zero`` per input port.  Assign directly as a method:
    ``MyNode.exchange_routes = cluster.route_all_to_zero``."""
    return [route_to_zero] * max(1, len(node.inputs))


def route_by(fn: Callable[[Any, tuple], Any]) -> Callable[[Any], int]:
    """Route by a computed co-location value (group values, join key,
    instance)."""

    def route(u: Any) -> int:
        vals = fn(u.key, u.values)
        if isinstance(vals, tuple):
            return stable_shard(*vals)
        return stable_shard(vals)

    return route
