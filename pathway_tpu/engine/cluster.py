"""Multi-worker execution: worker topology + collective exchange.

The reference scales out by running the identical dataflow on every worker
and exchanging records so that each stateful operator only keeps the rows
whose shard hash it owns (timely exchange channels: shared memory between
threads, TCP between processes — ``src/engine/dataflow.rs:1068-1072``,
``src/engine/dataflow/config.rs:67-120``).  This module provides the same
capability for the epoch-synchronous engine:

- :class:`Cluster` — ``threads × processes`` workers.  Worker ``w`` lives in
  process ``w // threads``.  Intra-process exchange is shared memory behind
  a barrier; inter-process exchange is a TCP full mesh on
  ``127.0.0.1:first_port+pid`` (reference ``CommunicationConfig::Cluster``).
- ``exchange(slot, outboxes)`` — all-to-all for one (node, port, epoch):
  every worker deposits one outbox per destination worker and receives the
  concatenation of what all workers sent it, merged in global worker order
  (deterministic, so N-worker runs produce the same output as 1-worker).
- ``allgather(slot, obj)`` — small-object gather used for the epoch-cut
  consensus: every worker receives the list of all workers' statuses and
  applies the same decision function, so no asymmetric coordinator
  broadcast is needed.

A worker failure surfaces as a broken socket on every peer, failing the
whole run — the reference behaves the same (a worker panic aborts the
cluster, ``dataflow.rs:5533-5536``); recovery is restart-from-persistence.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time as _time
from typing import Any, Callable

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native_mod

__all__ = ["Cluster", "stable_shard"]


def stable_shard(*values: Any) -> int:
    """Process-stable shard hash of a tuple of cell values (Python's
    builtin ``hash`` is salted per process, so it cannot route rows
    consistently across a TCP cluster; the 128-bit key hash can)."""
    try:
        return int(K.ref_scalar(*values))
    except Exception:
        return int(K.ref_scalar(repr(values)))


class _ProcessLinks:
    """TCP full mesh between processes.  Process p listens on
    ``first_port + p``; every pair is connected once (higher pid dials
    lower pid).  Frames are length-prefixed pickles of ``(slot, payload)``;
    a reader thread per peer deposits frames into a slot-keyed inbox."""

    _CONNECT_TIMEOUT_S = 30.0

    def __init__(self, process_id: int, n_processes: int, first_port: int):
        self.process_id = process_id
        self.n_processes = n_processes
        self._socks: dict[int, socket.socket] = {}
        self._send_locks: dict[int, threading.Lock] = {}
        self._inbox: dict[Any, dict[int, Any]] = {}
        self._cv = threading.Condition()
        self._failed: str | None = None

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", first_port + process_id))
        listener.listen(n_processes)
        self._listener = listener

        accept_thread = threading.Thread(
            target=self._accept_peers, args=(listener,), daemon=True
        )
        accept_thread.start()
        # dial every lower pid (it is already listening or will be soon)
        for peer in range(process_id):
            self._socks[peer] = self._dial(peer, first_port)
        accept_thread.join(self._CONNECT_TIMEOUT_S)
        if len(self._socks) != n_processes - 1:
            raise RuntimeError(
                f"process {process_id}: cluster mesh incomplete "
                f"({len(self._socks)}/{n_processes - 1} peers)"
            )
        for peer, sock in self._socks.items():
            self._send_locks[peer] = threading.Lock()
            threading.Thread(
                target=self._read_loop, args=(peer, sock), daemon=True
            ).start()

    def _dial(self, peer: int, first_port: int) -> socket.socket:
        deadline = _time.monotonic() + self._CONNECT_TIMEOUT_S
        while True:
            try:
                sock = socket.create_connection(
                    ("127.0.0.1", first_port + peer), timeout=5.0
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(struct.pack("<I", self.process_id))
                return sock
            except OSError:
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"process {self.process_id}: cannot reach peer {peer}"
                    )
                _time.sleep(0.05)

    def _accept_peers(self, listener: socket.socket) -> None:
        expected = self.n_processes - 1 - self.process_id  # all higher pids
        listener.settimeout(self._CONNECT_TIMEOUT_S)
        for _ in range(expected):
            try:
                sock, _addr = listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = struct.unpack("<I", self._recv_exact(sock, 4))[0]
            self._socks[peer] = sock

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _read_loop(self, peer: int, sock: socket.socket) -> None:
        try:
            sock.settimeout(None)
            while True:
                header = self._recv_exact(sock, 8)
                (n,) = struct.unpack("<Q", header)
                frame = pickle.loads(self._recv_exact(sock, n))
                slot, payload = frame
                with self._cv:
                    self._inbox.setdefault(slot, {})[peer] = payload
                    self._cv.notify_all()
        except (ConnectionError, OSError) as e:
            with self._cv:
                self._failed = f"link to process {peer} lost: {e!r}"
                self._cv.notify_all()

    def send(self, peer: int, slot: Any, payload: Any) -> None:
        data = pickle.dumps((slot, payload), protocol=pickle.HIGHEST_PROTOCOL)
        with self._send_locks[peer]:
            self._socks[peer].sendall(struct.pack("<Q", len(data)) + data)

    def recv_from_all(self, slot: Any) -> dict[int, Any]:
        """Block until every peer delivered a payload for ``slot``."""
        with self._cv:
            while True:
                if self._failed is not None:
                    raise RuntimeError(f"cluster failure: {self._failed}")
                got = self._inbox.get(slot)
                if got is not None and len(got) == self.n_processes - 1:
                    return self._inbox.pop(slot)
                self._cv.wait(timeout=1.0)

    def close(self) -> None:
        for sock in self._socks.values():
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass


class Cluster:
    """Worker topology + collectives for ``threads × processes`` workers.

    Worker global index = ``process_id * threads + thread_id``.  Exchange
    within a process is shared memory; across processes one aggregated
    frame per peer per collective.
    """

    def __init__(
        self,
        *,
        threads: int = 1,
        processes: int = 1,
        process_id: int = 0,
        first_port: int = 10000,
    ):
        self.threads = threads
        self.processes = processes
        self.process_id = process_id
        self.n_workers = threads * processes
        self._links = (
            _ProcessLinks(process_id, processes, first_port)
            if processes > 1
            else None
        )
        self._barrier = threading.Barrier(threads)
        self._local: dict[Any, Any] = {}  # slot -> per-tid deposits
        self._merged: dict[Any, Any] = {}  # slot -> per-tid results
        self._lock = threading.Lock()

    def worker_index(self, thread_id: int) -> int:
        return self.process_id * self.threads + thread_id

    # ------------------------------------------------------------------
    def exchange(
        self, slot: Any, thread_id: int, outboxes: list[list]
    ) -> list:
        """All-to-all: ``outboxes[w]`` holds this worker's updates destined
        to global worker ``w``; returns the merged inbox for this worker,
        concatenated in global source-worker order."""
        T, P = self.threads, self.processes
        with self._lock:
            self._local.setdefault(slot, {})[thread_id] = outboxes
        self._barrier.wait()
        if thread_id == 0:
            local = self._local.pop(slot)
            # remote frame: ("b", payload) with payload[src_tid][dst_tid]
            # a binary update frame packed in one C++ pass (tagged
            # scalars; see native pack_updates) — the reference's timely
            # exchange serializes records in binary the same way
            # (external/timely-dataflow/communication/).  Without the
            # native module: ("p", nested lists of plain (int_key,
            # values, diff) tuples) — pickling the Pointer int-subclass
            # directly goes through per-object copyreg and measures ~6x
            # slower.  In-process workers share memory and skip all of
            # this.
            if self._links is not None:
                native = _native_mod.load()
                for peer in range(P):
                    if peer == self.process_id:
                        continue
                    payload: Any = None
                    if native is not None:
                        try:
                            payload = (
                                "b",
                                [
                                    [
                                        native.pack_updates(
                                            local[src_tid][peer * T + dst_tid]
                                        )
                                        for dst_tid in range(T)
                                    ]
                                    for src_tid in range(T)
                                ],
                            )
                        except Exception:
                            payload = None
                    if payload is None:
                        payload = (
                            "p",
                            [
                                [
                                    [
                                        (int(u[0]), u[1], u[2])
                                        for u in local[src_tid][peer * T + dst_tid]
                                    ]
                                    for dst_tid in range(T)
                                ]
                                for src_tid in range(T)
                            ],
                        )
                    self._links.send(peer, slot, payload)
                remote = self._links.recv_from_all(slot)
            else:
                remote = {}
            merged: list[list] = [[] for _ in range(T)]
            base = self.process_id * T
            for src_pid in range(P):
                for src_tid in range(T):
                    if src_pid == self.process_id:
                        boxes = local[src_tid]
                        for dst_tid in range(T):
                            merged[dst_tid].extend(boxes[base + dst_tid])
                    else:
                        kind, payload = remote[src_pid]
                        if kind == "b":
                            native = _native_mod.load()
                            if native is None:
                                # peer packed binary frames we cannot parse
                                # (native load failed only on THIS process,
                                # e.g. a corrupted build cache): fail loudly
                                # rather than AttributeError on None
                                raise RuntimeError(
                                    "cluster exchange: peer sent binary "
                                    "frames but the native module is "
                                    "unavailable in this process"
                                )
                            for dst_tid in range(T):
                                merged[dst_tid].extend(
                                    native.unpack_updates(
                                        payload[src_tid][dst_tid]
                                    )
                                )
                        else:
                            from pathway_tpu.engine.stream import Update
                            from pathway_tpu.internals.keys import Pointer

                            for dst_tid in range(T):
                                merged[dst_tid].extend(
                                    Update(Pointer(k), v, d)
                                    for k, v, d in payload[src_tid][dst_tid]
                                )
            with self._lock:
                self._merged[slot] = merged
        self._barrier.wait()
        with self._lock:
            merged = self._merged[slot]
            result = merged[thread_id]
            merged[thread_id] = None  # type: ignore[call-overload]
            if all(m is None for m in merged):
                self._merged.pop(slot, None)
        return result

    def allgather(self, slot: Any, thread_id: int, obj: Any) -> list:
        """Every worker contributes one object; every worker receives the
        list of all objects in global worker order.  Epoch-cut consensus
        applies the same pure decision function to this list everywhere."""
        T, P = self.threads, self.processes
        with self._lock:
            self._local.setdefault(slot, {})[thread_id] = obj
        self._barrier.wait()
        if thread_id == 0:
            local = self._local.pop(slot)
            if self._links is not None:
                payload = [local[tid] for tid in range(T)]
                for peer in range(P):
                    if peer != self.process_id:
                        self._links.send(peer, slot, payload)
                remote = self._links.recv_from_all(slot)
            else:
                remote = {}
            gathered: list = []
            for src_pid in range(P):
                if src_pid == self.process_id:
                    gathered.extend(local[tid] for tid in range(T))
                else:
                    gathered.extend(remote[src_pid])
            with self._lock:
                self._merged[slot] = gathered
        self._barrier.wait()
        with self._lock:
            gathered = self._merged[slot]
            # every thread reads the same list; last reader cleans up
            counter = self._local.setdefault(("__done__", slot), {"n": 0})
            counter["n"] += 1
            if counter["n"] == T:
                self._merged.pop(slot, None)
                self._local.pop(("__done__", slot), None)
        return gathered

    def close(self) -> None:
        self._barrier.abort()  # free local threads blocked in a collective
        if self._links is not None:
            self._links.close()


def route_by_key(u: Any) -> int:
    """Default co-location: the row key (already a 128-bit stable hash)."""
    return int(u.key)


#: native route_split spec: empty tuple = key-value routing (see
#: native/pathway_native.cpp py_route_split)
route_by_key.positional = ()  # type: ignore[attr-defined]


def route_to_zero(_u: Any) -> int:
    """Centralized operators (temporal buffers, external indexes, outputs):
    the reference shards these to a single worker too
    (``TimeKey::shard() -> 1``, ``src/engine/dataflow/operators/time_column.rs:44-52``)."""
    return 0


#: scheduler fast path: everything to worker 0 without a per-row call
route_to_zero.const_zero = True  # type: ignore[attr-defined]


def route_all_to_zero(node: Any) -> list:
    """``exchange_routes`` implementation for centralized operators: one
    ``route_to_zero`` per input port.  Assign directly as a method:
    ``MyNode.exchange_routes = cluster.route_all_to_zero``."""
    return [route_to_zero] * max(1, len(node.inputs))


def route_by(fn: Callable[[Any, tuple], Any]) -> Callable[[Any], int]:
    """Route by a computed co-location value (group values, join key,
    instance)."""

    def route(u: Any) -> int:
        vals = fn(u.key, u.values)
        if isinstance(vals, tuple):
            return stable_shard(*vals)
        return stable_shard(vals)

    return route
