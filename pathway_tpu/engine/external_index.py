"""External index dataflow operator.

Equivalent of the reference's ``use_external_index_as_of_now``
(``src/engine/graph.rs:915``, operator
``src/engine/dataflow/operators/external_index.rs``, framework
``src/external_integration/mod.rs:40-181``): an index side (documents)
feeds adds/retractions into an index object; a query side gets each
query answered against the index.

Two consistency modes:

- ``as_of_now=True`` (reference semantics): a query is answered ONCE
  against the index state at its arrival epoch; later index updates do
  not revise past answers.  Query retractions retract the cached answer.
- ``as_of_now=False`` (fully consistent ``DataIndex.query``): live
  queries are re-answered whenever the index changes, emitting
  retraction/addition diffs.

All queries of an epoch are answered in ONE batched ``search`` call —
on the TPU-backed index that is a single jitted matmul+top-k.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol, Sequence

from pathway_tpu.engine.graph import EngineGraph, Node
from pathway_tpu.engine.stream import Batch, Update, consolidate, per_key_changes
from pathway_tpu.internals import api
from pathway_tpu.internals.keys import Pointer


class IndexAdapter(Protocol):
    """Host-side index contract (reference ``trait ExternalIndex``,
    ``src/external_integration/mod.rs:40-48``)."""

    def add(self, items: Sequence[tuple[Any, Any]]) -> None: ...

    def remove(self, keys: Sequence[Any]) -> None: ...

    def search(
        self,
        payloads: Sequence[Any],
        k: Sequence[int],
        filters: Sequence[Callable[[dict], bool] | None],
    ) -> list[list[tuple[Any, float]]]: ...


class ExternalIndexNode(Node):
    """inputs = [index_side, query_side].

    Output row = query_values + (ids, scores, datas) where each of the
    three is a tuple aligned by rank; ``datas`` carries the indexed
    row's data snapshot taken at answer time.
    """

    def __init__(
        self,
        graph: EngineGraph,
        index_input: Node,
        query_input: Node,
        adapter: IndexAdapter,
        *,
        index_payload_fn: Callable[[Pointer, tuple], Any],
        index_data_fn: Callable[[Pointer, tuple], Any] | None = None,
        index_meta_fn: Callable[[Pointer, tuple], dict | None] | None = None,
        query_payload_fn: Callable[[Pointer, tuple], Any],
        query_k_fn: Callable[[Pointer, tuple], int],
        query_filter_fn: Callable[[Pointer, tuple], Any] | None = None,
        as_of_now: bool = True,
        name: str = "external_index",
    ):
        super().__init__(graph, [index_input, query_input], name)
        self.adapter = adapter
        self.index_payload_fn = index_payload_fn
        self.index_data_fn = index_data_fn or (lambda k, v: None)
        self.index_meta_fn = index_meta_fn or (lambda k, v: None)
        self.query_payload_fn = query_payload_fn
        self.query_k_fn = query_k_fn
        self.query_filter_fn = query_filter_fn or (lambda k, v: None)
        self.as_of_now = as_of_now

    def make_state(self):
        return {
            "docs": {},  # key -> (data, meta)
            "queries": {},  # live queries (non-as-of-now): key -> values
            "out": {},  # query key -> emitted result tuple
        }

    # ------------------------------------------------------------------
    def _apply_index_batch(self, st: dict, batch: Batch) -> bool:
        """Apply doc adds/removals to the adapter; True if anything changed."""
        if not batch:
            return False
        changes = per_key_changes(batch)
        removals: list[Any] = []
        additions: list[tuple[Any, Any]] = []
        for key, (rem, add) in changes.items():
            if add:
                values = add[-1]
                try:
                    payload = self.index_payload_fn(key, values)
                except Exception as e:  # noqa: BLE001
                    payload = None
                    self._log_error(f"index payload failed: {e!r}")
                if payload is None or payload is api.ERROR:
                    # unindexable row: drop (and forget any previous version)
                    if key in st["docs"]:
                        removals.append(key)
                        del st["docs"][key]
                    continue
                additions.append((key, payload))
                st["docs"][key] = (
                    self.index_data_fn(key, values),
                    self.index_meta_fn(key, values),
                )
            elif rem and key in st["docs"]:
                removals.append(key)
                del st["docs"][key]
        changed = False
        if removals:
            try:
                self.adapter.remove(removals)
                changed = True
            except Exception as e:  # noqa: BLE001
                self._log_error(f"index remove failed: {e!r}")
        if additions:
            try:
                self.adapter.add(additions)  # upsert semantics
                changed = True
                if hasattr(self.adapter, "set_meta"):
                    for key, _payload in additions:
                        self.adapter.set_meta(key, st["docs"][key][1])
            except Exception as e:  # noqa: BLE001
                # one bad batch must not abort the streaming run
                self._log_error(f"index add failed: {e!r}")
                for key, _payload in additions:
                    st["docs"].pop(key, None)
        return changed

    def _log_error(self, msg: str) -> None:
        self._ctx.log_error(self, f"{self.name}: {msg}")

    def _filter_for(self, key: Pointer, values: tuple):
        spec = self.query_filter_fn(key, values)
        if spec is None or spec is api.ERROR:
            return None
        if callable(spec):
            return spec
        from pathway_tpu.stdlib.indexing.filters import compile_filter

        return compile_filter(str(spec))

    def _answer(
        self, st: dict, items: list[tuple[Pointer, tuple]]
    ) -> list[tuple]:
        """Batched search; returns result column tuples aligned with items."""
        payloads, ks, filters = [], [], []
        for key, values in items:
            try:
                payloads.append(self.query_payload_fn(key, values))
            except Exception as e:  # noqa: BLE001
                self._log_error(f"query payload failed: {e!r}")
                payloads.append(None)
            try:
                k = int(self.query_k_fn(key, values))
            except Exception:
                k = 3
            ks.append(max(k, 0))
            try:
                filters.append(self._filter_for(key, values))
            except Exception as e:  # noqa: BLE001
                self._log_error(f"bad metadata filter: {e!r}")
                filters.append(None)
        # queries with unusable payloads get empty replies; the rest go to
        # the adapter in one batch
        clean = [i for i, p in enumerate(payloads) if p is not None and p is not api.ERROR]
        replies = [[] for _ in items]
        if clean:
            try:
                sub = self.adapter.search(
                    [payloads[i] for i in clean],
                    [ks[i] for i in clean],
                    [filters[i] for i in clean],
                )
                for i, r in zip(clean, sub):
                    replies[i] = r
            except Exception as e:  # noqa: BLE001
                self._log_error(f"search failed: {e!r}")
        out = []
        for reply in replies:
            ids = tuple(k for k, _ in reply)
            scores = tuple(float(s) for _, s in reply)
            datas = tuple(
                st["docs"].get(k, (None, None))[0] for k, _ in reply
            )
            out.append((ids, scores, datas))
        return out

    # ------------------------------------------------------------------
    # persistence: the adapter's index is large out-of-band state — fold
    # a serialized copy into the operator snapshot so a restarted worker
    # restores it at the checkpointed epoch and replays only the tail
    # instead of re-embedding/re-inserting the whole corpus

    def snapshot_state(self, ctx):
        if getattr(ctx, "worker_id", 0) != 0:
            return None  # route_all_to_zero: worker 0 owns the index
        sd = getattr(self.adapter, "state_dict", None)
        if sd is None:
            return None
        st = ctx.state(self)
        return {**st, "__index__": sd()}

    def on_restore(self, ctx):
        st = ctx.states.get(self.id)
        if not isinstance(st, dict):
            return
        index_state = st.pop("__index__", None)
        if index_state is None:
            return
        load = getattr(self.adapter, "load_state_dict", None)
        if load is not None:
            load(index_state)

    # ------------------------------------------------------------------
    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        self._ctx = ctx
        index_changed = self._apply_index_batch(st, inbatches[0])
        out: list[Update] = []

        qbatch = consolidate(inbatches[1])
        added: list[tuple[Pointer, tuple]] = []
        for u in qbatch:
            if u.diff > 0:
                added.append((u.key, u.values))
                if not self.as_of_now:
                    st["queries"][u.key] = u.values
            else:
                if not self.as_of_now:
                    st["queries"].pop(u.key, None)
                prev = st["out"].pop(u.key, None)
                if prev is not None:
                    out.append(Update(u.key, prev, -1))

        recompute: list[tuple[Pointer, tuple]] = list(added)
        if not self.as_of_now and index_changed:
            added_keys = {k for k, _ in added}
            recompute += [
                (k, v) for k, v in st["queries"].items() if k not in added_keys
            ]

        if recompute:
            results = self._answer(st, recompute)
            for (key, values), res in zip(recompute, results):
                row = values + res
                prev = st["out"].get(key)
                if prev == row:
                    continue
                if prev is not None:
                    out.append(Update(key, prev, -1))
                out.append(Update(key, row, 1))
                st["out"][key] = row
        if self.as_of_now:
            # answered queries need no further state unless retracted later;
            # keep out-cache only (it backs retraction replay)
            pass
        return consolidate(out)


# index + queries live on worker 0: the device-plane slab has one host
# owner (the reference replicates indexes per worker instead, which a
# single shared TPU slab replaces)
from pathway_tpu.engine import cluster as _cl

ExternalIndexNode.exchange_routes = _cl.route_all_to_zero
