"""Columnar epoch batches: the Python-side handle for native frames.

A :class:`ColumnarBatch` is an ordered list of *segments*, each either a
native frame capsule (``("f", capsule)`` — contiguous typed columns with
an interned string pool, built by ``native.frame_parse_jsonl`` or
``native.frame_from_updates``) or a plain row list (``("r", [Update])``).
It quacks like the row list the engine has always passed between
operators — ``len``, truthiness, iteration — so every operator that does
not understand frames can call :meth:`to_list` (or just iterate) and run
its existing row-at-a-time path, while frame-aware operators
(``InputNode``, ``GroupByNode``, the exchange router) consume the frame
segments with one native kernel call per segment.

The representation mirrors the reference engine's batched arrangements
(Rust differential ships (data, time, diff) *batches* between operators,
never per-row boxed values); the row-list fallback is this
reproduction's Python-UDF escape hatch.
"""

from __future__ import annotations

import os
from typing import Any, Iterable, Iterator

from pathway_tpu.internals import native as _native


def columnar_enabled() -> bool:
    """Global gate: ``PATHWAY_DISABLE_COLUMNAR=1`` forces every operator
    onto the row path (the bench harness uses it for the columnar-vs-row
    smoke gate; also the escape hatch if a frame kernel misbehaves)."""
    return os.environ.get("PATHWAY_DISABLE_COLUMNAR", "") != "1" and (
        _native.load() is not None
    )


class ColumnarBatch:
    """Epoch delta as a sequence of frame/row segments (order preserved:
    iteration yields updates in exactly the order a pure row pipeline
    would have produced them)."""

    __slots__ = ("segments",)

    def __init__(self, segments: list[tuple[str, Any]] | None = None):
        self.segments: list[tuple[str, Any]] = (
            segments if segments is not None else []
        )

    # -- construction ---------------------------------------------------

    @classmethod
    def from_rows(cls, rows: list) -> "ColumnarBatch":
        return cls([("r", rows)] if rows else [])

    def append_frame(self, cap: Any) -> None:
        native = _native.load()
        if native.frame_len(cap):
            self.segments.append(("f", cap))

    def append(self, u: Any) -> None:
        self._tail_rows().append(u)

    def extend(self, rows: Iterable[Any]) -> None:
        if isinstance(rows, ColumnarBatch):
            # merge adjacent row segments so a frame/row/frame interleave
            # does not fragment into many tiny lists
            for kind, seg in rows.segments:
                if kind == "r":
                    self._tail_rows().extend(seg)
                else:
                    self.segments.append((kind, seg))
            return
        rows = list(rows)
        if rows:
            self._tail_rows().extend(rows)

    def _tail_rows(self) -> list:
        if self.segments and self.segments[-1][0] == "r":
            return self.segments[-1][1]
        rows: list = []
        self.segments.append(("r", rows))
        return rows

    # -- row-list protocol ----------------------------------------------

    def __len__(self) -> int:
        native = _native.load()
        n = 0
        for kind, seg in self.segments:
            n += native.frame_len(seg) if kind == "f" else len(seg)
        return n

    def __bool__(self) -> bool:
        # frame segments are non-empty by construction (append_frame
        # drops empties), so any frame segment means data
        return any(
            kind == "f" or bool(seg) for kind, seg in self.segments
        )

    def __iter__(self) -> Iterator[Any]:
        native = _native.load()
        for kind, seg in self.segments:
            if kind == "f":
                yield from native.frame_to_updates(seg)
            else:
                yield from seg

    def to_list(self) -> list:
        """Materialize every segment into one flat Update list — the
        row-path fallback.  Each call builds fresh rows (frames are
        immutable; no caching, so no aliasing between consumers)."""
        native = _native.load()
        out: list = []
        for kind, seg in self.segments:
            if kind == "f":
                out.extend(native.frame_to_updates(seg))
            else:
                out.extend(seg)
        return out

    # -- engine helpers -------------------------------------------------

    def frame_rows(self) -> int:
        """Rows held in frame segments (the columnar-path telemetry)."""
        native = _native.load()
        return sum(
            native.frame_len(seg)
            for kind, seg in self.segments
            if kind == "f"
        )

    def all_plus(self) -> bool:
        """True iff every update in the batch has diff +1 (frame header
        flag for frame segments, a scan for row segments)."""
        native = _native.load()
        for kind, seg in self.segments:
            if kind == "f":
                if not native.frame_all_plus(seg):
                    return False
            elif not native.all_positive(seg):
                return False
        return True

    def split(self, n: int) -> "tuple[ColumnarBatch, ColumnarBatch]":
        """(first n updates, rest) — the epoch row-budget split.  Frame
        segments split by ``frame_slice`` (string pool shared, keys stay
        lazy), so a budget cut through a million-row frame costs two
        column copies, not a materialization."""
        native = _native.load()
        head = ColumnarBatch()
        tail = ColumnarBatch()
        left = n
        for kind, seg in self.segments:
            if left <= 0:
                tail.segments.append((kind, seg))
                continue
            size = native.frame_len(seg) if kind == "f" else len(seg)
            if size <= left:
                head.segments.append((kind, seg))
                left -= size
            elif kind == "f":
                head.append_frame(native.frame_slice(seg, 0, left))
                tail.append_frame(native.frame_slice(seg, left, size))
                left = 0
            else:
                head.segments.append(("r", seg[:left]))
                tail.segments.append(("r", seg[left:]))
                left = 0
        return head, tail


def extend_batch(buf: Any, more: Any) -> Any:
    """Append ``more`` (rows or ColumnarBatch) onto ``buf`` (list or
    ColumnarBatch), promoting the buffer to columnar when frame data
    arrives; returns the (possibly new) buffer.  The single seam through
    which the scheduler's buffers, fan-out, and exchange merges stay
    frame-preserving."""
    if isinstance(more, ColumnarBatch):
        if not isinstance(buf, ColumnarBatch):
            buf = ColumnarBatch.from_rows(buf)
        buf.extend(more)
        return buf
    if isinstance(buf, ColumnarBatch):
        buf.extend(more)
        return buf
    buf.extend(more)
    return buf
