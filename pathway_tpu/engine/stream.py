"""Update-stream primitives.

The engine models every table as a stream of keyed row updates
``(key, values, diff)`` grouped into *epochs* (logical timestamps).  This is
the capability of the reference's differential collections
(``src/engine/dataflow.rs``) re-expressed for an epoch-synchronous scheduler:
within one epoch all operators see a consistent atomic batch; retractions are
``diff=-1`` updates.

Timestamps are even integers advancing by 2, matching the reference's
convention of reserving odd times for internal interleaving
(``src/connectors/mod.rs:199,538,552``).
"""

from __future__ import annotations

import datetime
import json
from typing import Any, Iterable, NamedTuple

import numpy as np

from pathway_tpu.internals import native as _native
from pathway_tpu.internals.keys import Pointer


class Update(NamedTuple):
    key: Pointer
    values: tuple
    diff: int


Batch = list[Update]

TIME_STEP = 2


def hashable(value: Any) -> Any:
    """Map an arbitrary cell value to something hashable (for multiset
    counters inside reducers)."""
    if isinstance(value, np.ndarray):
        return ("__ndarray__", value.shape, value.tobytes())
    if isinstance(value, dict):
        return ("__dict__", json.dumps(value, sort_keys=True, default=str))
    if isinstance(value, list):
        return ("__list__", tuple(hashable(v) for v in value))
    if isinstance(value, tuple):
        return tuple(hashable(v) for v in value)
    return value


def hashable_row(values: tuple) -> tuple:
    return tuple(hashable(v) for v in values)


def _py_consolidate(batch: Iterable[Update]) -> Batch:
    acc: dict[tuple, list] = {}
    for u in batch:
        k = (u.key, u.values)
        try:
            e = acc.get(k)
        except TypeError:
            k = (u.key, hashable_row(u.values))
            e = acc.get(k)
        if e is None:
            acc[k] = [u.key, u.values, u.diff]
        else:
            e[2] += u.diff
    return [Update(key, vals, d) for key, vals, d in acc.values() if d != 0]


def consolidate(batch: Iterable[Update]) -> Batch:
    """Merge updates with equal (key, row), dropping zero-diff entries.

    Fast path hashes the row tuple directly (scalar cells — the common
    case); rows holding unhashable cells (ndarray/dict/list) fall back to
    the type-tagged :func:`hashable_row` per update, so both spellings of
    an equal row land in the same bucket.

    Runs in C when the native extension is available
    (``native/pathway_native.cpp`` ``consolidate`` — the compaction loop
    the reference runs inside differential arrangements); unchanged
    single-occurrence updates are re-emitted by reference, so the common
    no-duplicate case allocates nothing.  The C path handles unhashable
    rows itself (via ``hashable_row``), so it needs no fallback."""
    native = _native.load()
    if native is not None:
        return native.consolidate(
            batch if isinstance(batch, list) else list(batch),
            Update,
            hashable_row,
        )
    return _py_consolidate(batch)


def per_key_changes(batch: Iterable[Update]) -> dict[Pointer, tuple[list, list]]:
    """Group a batch into per-key (removals, additions) lists."""
    native = _native.load()
    if native is not None:
        return native.per_key_changes(batch)
    out: dict[Pointer, tuple[list, list]] = {}
    for u in batch:
        rem, add = out.setdefault(u.key, ([], []))
        if u.diff < 0:
            rem.extend([u.values] * (-u.diff))
        else:
            add.extend([u.values] * u.diff)
    return out


def total_str(value: Any) -> str:
    if isinstance(value, datetime.datetime):
        return value.isoformat()
    return str(value)
